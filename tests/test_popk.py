"""K-way microstep pop: equivalence gate (`experimental.microstep_events`).

The contract (ops/events.py `pop_k`/`clear_popped` + core/engine.py
`_microstep_k`) is *bit-identical behavior* to the single-event microstep —
same execution order, digests, per-host event counts, and drop counters —
with up to K events per host folded through one queue dispatch. These tests
are the determinism gate for that claim:

  1. a per-op property test drives `pop_k` against K sequential `q_pop_min`
     calls on randomly occupied queues (flat AND bucketed, both backend
     formulations), including partial-prefix clears and the bucketed
     block-min invariant after every clear;
  2. a reserve property test: the K-way push pass's capacity holds
     reproduce sequential push_one drop decisions exactly;
  3. engine-level digest equality for K in {1, 4, 8} on echo, phold, and
     tgen workloads — phold tuned so pushed jobs mature INSIDE the window
     (bursty in-window pushes), which forces the deferral guard to fire
     (asserted via stats.popk_deferred > 0) while histories stay identical;
  4. a checkpoint round-trip with K > 1 resumes to the same digest, and a
     checkpoint written under a different K refuses to restore.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shadow_tpu.ops import (
    as_flat,
    block_minima,
    bucket_rebuild,
    clear_popped,
    make_bucket_queue,
    make_queue,
    pack_order,
    pop_k,
    pop_min,
    push_many,
    bq_push_many,
    q_pop_min,
)
from shadow_tpu.ops.events import EVENT_PAYLOAD_WORDS
from shadow_tpu.simtime import TIME_MAX

from tests.engine_harness import mk_hosts, build_sim


def _random_queue(rng, hh, cc, bucket_block=0, fill_p=0.6):
    """A queue with random occupancy, unique order keys, random times."""
    q = make_bucket_queue(hh, cc, bucket_block) if bucket_block else make_queue(hh, cc)
    push = bq_push_many if bucket_block else push_many
    seq = 0
    for _ in range(3):
        pushes = []
        for _ in range(3):
            mask = jnp.asarray(rng.random(hh) < fill_p)
            t = jnp.asarray(rng.integers(1, 1000, hh), jnp.int64)
            order = jnp.asarray(
                [int(pack_order(1, i, seq + 11 * i)) for i in range(hh)],
                jnp.int64,
            )
            seq += 1
            kind = jnp.asarray(rng.integers(0, 5, hh), jnp.int32)
            payload = jnp.asarray(
                rng.integers(0, 99, (hh, EVENT_PAYLOAD_WORDS)), jnp.int32
            )
            pushes.append((mask, t, order, kind, payload))
        q = push(q, pushes)
    return q


# ------------------------------------------------------------------ property


@pytest.mark.parametrize("path", ["gather", "onehot"])
@pytest.mark.parametrize("block", [0, 2, 4])
@pytest.mark.parametrize("k", [1, 3, 8])
def test_pop_k_equals_sequential_pop_min(k, block, path):
    """Column j of `pop_k` must equal the j-th successive `q_pop_min`
    (events AND active masks), for scalar and per-host limits, and clearing
    the full active prefix must leave the identical slab — flat and
    bucketed, both extraction formulations, K from degenerate 1 to
    capacity."""
    hh, cc = 7, 8
    rng = np.random.default_rng(1000 * k + 10 * block + (path == "onehot"))
    for limit in (TIME_MAX, 500, jnp.asarray(rng.integers(1, 1000, hh), jnp.int64)):
        q = _random_queue(rng, hh, cc, bucket_block=block)
        popped = pop_k(q, limit, k, force_path=path)
        ref = q
        for j in range(k):
            ref, ev, act = q_pop_min(ref, limit)
            msg = f"k={k} block={block} path={path} col {j}"
            np.testing.assert_array_equal(
                np.asarray(act), np.asarray(popped.active[:, j]), err_msg=msg
            )
            for fa, fb, name in zip(
                ev, (popped.t[:, j], popped.order[:, j], popped.kind[:, j],
                     popped.payload[:, j]), ev._fields,
            ):
                np.testing.assert_array_equal(
                    np.asarray(fa), np.asarray(fb), err_msg=f"ev.{name} {msg}"
                )
        m = jnp.sum(popped.active.astype(jnp.int32), axis=1)
        cleared = clear_popped(q, popped, m)
        np.testing.assert_array_equal(
            np.asarray(as_flat(cleared).t), np.asarray(as_flat(ref).t)
        )
        np.testing.assert_array_equal(
            np.asarray(as_flat(cleared).order), np.asarray(as_flat(ref).order)
        )
        if block:
            bt, bo, bfill = block_minima(
                cleared.t, cleared.order, cleared.bt.shape[1]
            )
            np.testing.assert_array_equal(np.asarray(cleared.bt), np.asarray(bt))
            np.testing.assert_array_equal(np.asarray(cleared.bo), np.asarray(bo))
            np.testing.assert_array_equal(
                np.asarray(cleared.bfill), np.asarray(bfill)
            )


@pytest.mark.parametrize("block", [0, 4])
def test_clear_popped_partial_prefix(block):
    """Clearing only the first m events (the K-way deferral case) must
    equal m sequential pops — deferred events stay in the slab untouched
    and the bucketed caches stay coherent."""
    hh, cc, k = 5, 8, 6
    rng = np.random.default_rng(7 + block)
    q = _random_queue(rng, hh, cc, bucket_block=block, fill_p=0.9)
    popped = pop_k(q, TIME_MAX, k)
    m = jnp.asarray(rng.integers(0, k + 1, hh), jnp.int32)
    m = jnp.minimum(m, jnp.sum(popped.active.astype(jnp.int32), axis=1))
    cleared = clear_popped(q, popped, m)
    ref = q
    m_np = np.asarray(m)
    for j in range(k):
        refn, _, _ = q_pop_min(ref, TIME_MAX)
        # apply the j-th pop only on hosts whose prefix reaches past j
        take = jnp.asarray(m_np > j)
        # the per-host where() desyncs nothing: pops are row-local, so
        # masking whole rows keeps each row (slab AND caches) consistent
        ref = jax.tree.map(
            lambda new, old: jnp.where(
                take.reshape((hh,) + (1,) * (new.ndim - 1)), new, old
            ),
            refn, ref,
        )
    np.testing.assert_array_equal(
        np.asarray(as_flat(cleared).t), np.asarray(as_flat(ref).t)
    )
    np.testing.assert_array_equal(
        np.asarray(as_flat(cleared).order), np.asarray(as_flat(ref).order)
    )
    if block:
        bt, bo, bfill = block_minima(cleared.t, cleared.order, cleared.bt.shape[1])
        np.testing.assert_array_equal(np.asarray(cleared.bt), np.asarray(bt))
        np.testing.assert_array_equal(np.asarray(cleared.bo), np.asarray(bo))
        np.testing.assert_array_equal(np.asarray(cleared.bfill), np.asarray(bfill))


@pytest.mark.parametrize("bucket", [False, True])
def test_push_reserve_reproduces_sequential_drops(bucket):
    """The K-way fold's reserve (6th push-tuple element) must reproduce the
    K=1 drop decisions: a push sees free capacity minus the batch events
    that executed after it. Scenario: capacity 4, host holds 4 events, the
    first executed event pushes 2 — in K=1 the second push drops (only one
    slot was free then); a reserve-less fused pass would let it through."""
    hh, cc = 2, 4
    q = make_bucket_queue(hh, cc, 2) if bucket else make_queue(hh, cc)
    push = bq_push_many if bucket else push_many
    ones = jnp.ones((hh,), bool)
    fills = []
    for s in range(4):
        fills.append((
            ones, jnp.full((hh,), 10 * (s + 1), jnp.int64),
            jnp.asarray([int(pack_order(1, i, s)) for i in range(hh)], jnp.int64),
            jnp.ones((hh,), jnp.int32),
            jnp.zeros((hh, EVENT_PAYLOAD_WORDS), jnp.int32),
        ))
    q = push(q, fills)  # full queue
    popped = pop_k(q, TIME_MAX, 4)
    # all 4 events execute; event 0 emits two pushes -> reserves are 3
    m = jnp.full((hh,), 4, jnp.int32)
    q = clear_popped(q, popped, m)
    reserve = jnp.full((hh,), 3, jnp.int32)  # events 1..3 executed after 0
    p1 = (ones, jnp.full((hh,), 100, jnp.int64),
          jnp.asarray([int(pack_order(1, i, 10)) for i in range(hh)], jnp.int64),
          jnp.ones((hh,), jnp.int32),
          jnp.zeros((hh, EVENT_PAYLOAD_WORDS), jnp.int32), reserve)
    p2 = (ones, jnp.full((hh,), 101, jnp.int64),
          jnp.asarray([int(pack_order(1, i, 11)) for i in range(hh)], jnp.int64),
          jnp.ones((hh,), jnp.int32),
          jnp.zeros((hh, EVENT_PAYLOAD_WORDS), jnp.int32), reserve)
    q2 = push(q, [p1, p2])
    # K=1 ground truth: when event 0 pushed, events 1-3 still held slots,
    # so exactly ONE free slot existed: p1 lands, p2 drops.
    assert int(np.asarray(as_flat(q2).t == 100).sum()) == hh, "p1 must land"
    assert int(np.asarray(as_flat(q2).t == 101).sum()) == 0, "p2 must drop"
    np.testing.assert_array_equal(np.asarray(q2.dropped), np.full(hh, 1))
    if bucket:
        bt, bo, bfill = block_minima(q2.t, q2.order, q2.bt.shape[1])
        np.testing.assert_array_equal(np.asarray(q2.bt), np.asarray(bt))
        np.testing.assert_array_equal(np.asarray(q2.bfill), np.asarray(bfill))


# ------------------------------------------------------- engine determinism


def _run(model, hosts, stop, k, qb=0, **kw):
    cfg, m, params, mstate, events = build_sim(
        model, hosts, stop, world=1, queue_block=qb,
        microstep_events=k, **kw
    )
    from shadow_tpu.core import Engine

    eng = Engine(cfg, m, None)
    state, params = eng.init_state(params, mstate, events, seed=1)
    chunks = 0
    while not bool(state.done):
        state = eng.run_chunk(state, params)
        chunks += 1
        assert chunks < 500
    return jax.device_get(state.stats), np.asarray(
        jax.device_get(state.queue.dropped)
    )


# phold with pushes maturing INSIDE the 50 ms window (mean_delay 20 ms):
# the deferral guard must fire (a matured job's key precedes the next
# batch event) and histories must stay identical anyway
_CASES = [
    ("phold", mk_hosts(10, {"mean_delay": "20 ms", "population": 3}),
     400_000_000, dict(loss=0.1)),
    ("udp_echo",
     [dict(host_id=0, name="server", start_time=0,
           model_args={"role": "server"})]
     + [dict(host_id=i, name=f"c{i}", start_time=0,
             model_args={"role": "client", "peer": "server",
                         "interval": "4 ms", "size_bytes": 2000})
        for i in range(1, 5)],
     300_000_000, dict(bw_bits=2_000_000, loss=0.05, use_codel=True)),
    ("tgen_tcp",
     mk_hosts(6, {"flow_segs": 12, "flows": 1, "cwnd_cap": 8,
                  "rto_min": "100 ms"}),
     4_000_000_000, dict(loss=0.05, latency=10_000_000, sends_budget=16)),
]


@pytest.mark.parametrize(
    "model,hosts,stop,kw", _CASES, ids=["phold_bursty", "echo", "tgen_tcp"]
)
def test_engine_digest_k1_vs_kway(model, hosts, stop, kw):
    """The ISSUE acceptance gate: digests, per-host event counts, and drop
    counters bit-identical between K=1 and K in {4, 8}, flat queue."""
    s1, d1 = _run(model, hosts, stop, 1, **kw)
    deferred_any = 0
    for k in (4, 8):
        sk, dk = _run(model, hosts, stop, k, **kw)
        np.testing.assert_array_equal(
            np.asarray(s1.digest), np.asarray(sk.digest),
            err_msg=f"{model} K={k}",
        )
        np.testing.assert_array_equal(
            np.asarray(s1.events), np.asarray(sk.events),
            err_msg=f"{model} K={k} per-host events",
        )
        np.testing.assert_array_equal(d1, dk, err_msg=f"{model} K={k} drops")
        assert int(np.asarray(s1.pkts_budget_dropped).sum()) == int(
            np.asarray(sk.pkts_budget_dropped).sum()
        )
        # the fold actually folded: fewer dispatches for the same events
        assert int(np.asarray(sk.microsteps).sum()) <= int(
            np.asarray(s1.microsteps).sum()
        )
        deferred_any += int(np.asarray(sk.popk_deferred).sum())
    if model == "phold":  # the bursty-push case MUST exercise the guard
        assert deferred_any > 0, "deferral guard never fired on bursty phold"


def test_engine_digest_kway_bucketed():
    """K-way fold on the two-level bucketed queue (victim-block cache
    recompute path): digest-identical to flat K=1 on the tgen workload."""
    model, hosts, stop, kw = _CASES[2]
    s1, d1 = _run(model, hosts, stop, 1, **kw)
    sk, dk = _run(model, hosts, stop, 4, qb=8, **kw)
    np.testing.assert_array_equal(np.asarray(s1.digest), np.asarray(sk.digest))
    np.testing.assert_array_equal(d1, dk)
    assert int(np.asarray(sk.bq_rebuilds).sum()) > 0  # two-level path ran


def test_kway_mesh_invariant():
    """K-way folding is shard-local (no collectives inside the microstep
    loop), so digests must stay bit-identical across mesh shapes — and
    equal to the single-device K=1 run."""
    from shadow_tpu.core import Engine
    import jax as _jax

    hosts = mk_hosts(16, {"mean_delay": "20 ms", "population": 2})

    def run_world(world, k):
        cfg, m, params, mstate, events = build_sim(
            "phold", hosts, 300_000_000, world=world, loss=0.1,
            microstep_events=k,
        )
        mesh = None
        if world > 1:
            mesh = _jax.sharding.Mesh(
                np.array(_jax.devices()[:world]), ("hosts",)
            )
        eng = Engine(cfg, m, mesh)
        state, params = eng.init_state(params, mstate, events, seed=1)
        chunks = 0
        while not bool(state.done):
            state = eng.run_chunk(state, params)
            chunks += 1
            assert chunks < 500
        return np.asarray(jax.device_get(state.stats.digest))

    base = run_world(1, 1)
    np.testing.assert_array_equal(base, run_world(1, 4))
    np.testing.assert_array_equal(base, run_world(4, 4))


def test_kway_with_cpu_model():
    """CPU-delay deferral: a batch stops folding when the host's busy
    horizon crosses the window (K=1 would stop popping), keeping the
    busy-shifted execution times bit-identical."""
    hosts = mk_hosts(8, {"mean_delay": "30 ms", "population": 3})
    s1, d1 = _run("phold", hosts, 300_000_000, 1, cpu_delay_ns=2_000_000)
    s4, d4 = _run("phold", hosts, 300_000_000, 4, cpu_delay_ns=2_000_000)
    np.testing.assert_array_equal(np.asarray(s1.digest), np.asarray(s4.digest))
    np.testing.assert_array_equal(d1, d4)


# ----------------------------------------------------------------- restore


_CKPT_KWAY_SCRIPT = """
import json, sys
from shadow_tpu.config.options import ConfigOptions
from shadow_tpu.core.checkpoint import (
    CheckpointError, load_checkpoint, save_checkpoint,
)
from shadow_tpu.sim import Simulation


def cfg(k=4):
    return ConfigOptions.from_dict({
        "general": {"stop_time": "4 s", "seed": 23},
        "network": {"graph": {"type": "1_gbit_switch"}},
        "experimental": {"event_queue_capacity": 16,
                         "microstep_events": k},
        "hosts": {
            "n": {
                "count": 8,
                "network_node_id": 0,
                "processes": [{
                    "model": "phold",
                    "model_args": {"population": 2,
                                   "mean_delay": "100 ms"},
                }],
            }
        },
    })


a = Simulation(cfg(), world=1)
a.run(progress=False)
digest_a = a.stats_report()["determinism_digest"]

b = Simulation(cfg(), world=1)
b.state = b.engine.run_chunk(b.state, b.params)
assert not bool(b.state.done)
ckpt = sys.argv[1]
save_checkpoint(ckpt, b)

c = Simulation(cfg(), world=1)
load_checkpoint(ckpt, c)
c.run(progress=False)

d = Simulation(cfg(k=2), world=1)  # different K: refuse loudly
try:
    load_checkpoint(ckpt, d)
    refused = False
except CheckpointError:
    refused = True
print(json.dumps({"digest_a": digest_a,
                  "digest_c": c.stats_report()["determinism_digest"],
                  "refused": refused}))
"""


def test_checkpoint_roundtrip_kway(tmp_path):
    """A K>1 sim checkpointed mid-run resumes to the digest of an
    uninterrupted run; a checkpoint written under a different K refuses
    (EngineConfig participates in the fingerprint). Runs three compiled
    `Simulation`s, so the whole leg lives in the subprocess harness (this
    box's corruption reliably SIGABRTs it in-process, killing pytest —
    tests/subproc.py); a completed-child digest mismatch gets one fresh
    rerun before failing (the scribble flavor of the same corruption)."""
    from tests.subproc import run_isolated_json

    out = run_isolated_json(
        _CKPT_KWAY_SCRIPT, str(tmp_path / "popk.npz")
    )
    assert out["refused"] is True
    if out["digest_c"] != out["digest_a"]:
        out = run_isolated_json(
            _CKPT_KWAY_SCRIPT, str(tmp_path / "popk2.npz")
        )
        assert out["refused"] is True
    assert out["digest_c"] == out["digest_a"]
