"""Device-side TCP lanes (tgen_tcp model): handshake, Reno dynamics,
loss recovery, determinism, and cross-checks against the golden oracle
(VERDICT r4 missing #1; reference src/test/tgen + src/lib/tcp)."""

from __future__ import annotations

import pytest

from shadow_tpu.config.options import ConfigOptions
from shadow_tpu.sim import Simulation

GML = """
graph [ directed 0
  node [ id 0 host_bandwidth_down "1 Gbit" host_bandwidth_up "1 Gbit" ]
  edge [ source 0 target 0 latency "10 ms" packet_loss %s ]
]"""


def _cfg(n=4, stop="30 s", seed=7, loss="0.0", sched="tpu", flows=1,
         flow_segs=40, extra_args=None, capacity=64, budget=24):
    args = {"flow_segs": flow_segs, "flows": flows, "cwnd_cap": 8,
            "rto_min": "100 ms"}
    if extra_args:
        args.update(extra_args)
    return ConfigOptions.from_dict(
        {
            "general": {"stop_time": stop, "seed": seed},
            "network": {"graph": {"type": "gml", "inline": GML % loss}},
            "experimental": {
                "scheduler": sched,
                "event_queue_capacity": capacity,
                "sends_per_host_round": budget,
            },
            "hosts": {
                "peer": {
                    "count": n,
                    "network_node_id": 0,
                    "processes": [{"model": "tgen_tcp", "model_args": args}],
                }
            },
        }
    )


def test_lossless_transfer_no_retransmits():
    """Clean network: every flow completes, exactly flow_segs first
    transmissions per flow, zero retransmits/timeouts (the analogue of the
    reference tgen fixed_size test's byte-count assertion)."""
    sim = Simulation(_cfg(), world=1)
    r = sim.run(progress=False)
    m = r["model_report"]
    assert m["flows_completed"] == m["flows_expected"] == 4
    assert m["data_segments_sent"] == 4 * 40
    assert m["retransmits"] == 0
    assert m["timeouts"] == 0
    assert m["payload_bytes_received"] == 4 * 40 * 1460
    # closed-form Reno cross-check (the scalar-analysis analogue of diffing
    # against the CPU-plane machine): SYN+SYNACK = 1 RTT, then slow start
    # from cwnd_init=2 capped at cwnd_cap=8 sends 2,4,8,8,8,8,2 segments
    # = 40 over 7 RTTs (the last window's ACK completes in the 6th), FIN +
    # FINACK = 1 more; total = 9 RTT = 180 ms at 20 ms RTT, zero queueing
    assert m["mean_fct_ms"] == pytest.approx(180.0, abs=2.0)


def test_lossy_transfer_recovers_and_completes():
    """5% loss: flows still complete; recovery happens via retransmits
    (fast retransmit and/or RTO), and the receiver saw every segment."""
    sim = Simulation(_cfg(loss="0.05", stop="120 s", seed=3), world=1)
    r = sim.run(progress=False)
    m = r["model_report"]
    assert m["flows_completed"] == m["flows_expected"] == 4
    assert m["retransmits"] > 0
    assert m["payload_bytes_received"] == 4 * 40 * 1460
    assert r["packets_lost"] > 0


def test_fast_retransmit_under_light_loss():
    """At light loss with a wide-enough window, some recoveries must be
    dup-ACK-driven (fast retransmit), not all timeouts."""
    sim = Simulation(
        _cfg(loss="0.02", stop="240 s", seed=11, n=6, flow_segs=200,
             extra_args={"cwnd_cap": 16}, budget=40),
        world=1,
    )
    r = sim.run(progress=False)
    m = r["model_report"]
    assert m["flows_completed"] == 6
    assert m["fast_retransmits"] > 0


def test_matches_golden_oracle():
    dev = Simulation(_cfg(seed=5, loss="0.03", stop="60 s"), world=1).run(
        progress=False
    )
    gold = Simulation(
        _cfg(seed=5, loss="0.03", stop="60 s", sched="cpu-reference"),
        world=1,
    ).run(progress=False)
    assert dev["determinism_digest"] == gold["determinism_digest"]
    assert dev["model_report"] == gold["model_report"]


def test_mesh_invariant_under_loss():
    a = Simulation(_cfg(n=8, seed=9, loss="0.03", stop="60 s"), world=1).run(
        progress=False
    )
    b = Simulation(_cfg(n=8, seed=9, loss="0.03", stop="60 s"), world=8).run(
        progress=False
    )
    assert a["determinism_digest"] == b["determinism_digest"]
    assert a["model_report"] == b["model_report"]


def test_all_to_all_phases():
    """flows = n-1 gives the full all-to-all: every host both sends to and
    serves every other host exactly once."""
    n = 4
    sim = Simulation(
        _cfg(n=n, flows=n - 1, flow_segs=12, stop="120 s"), world=1
    )
    r = sim.run(progress=False)
    m = r["model_report"]
    assert m["flows_completed"] == n * (n - 1)
    assert m["data_segments_sent"] == n * (n - 1) * 12
    assert m["payload_bytes_received"] == n * (n - 1) * 12 * 1460


def test_reruns_bit_identical():
    a = Simulation(_cfg(seed=2, loss="0.04"), world=1).run(progress=False)
    b = Simulation(_cfg(seed=2, loss="0.04"), world=1).run(progress=False)
    assert a["determinism_digest"] == b["determinism_digest"]


def test_needs_two_hosts():
    with pytest.raises(Exception, match="at least 2"):
        Simulation(_cfg(n=1), world=1)
