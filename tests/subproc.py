"""Subprocess isolation for compiled-`Simulation` test legs.

This box's jaxlib 0.4.37, under the 8-virtual-device conftest, intermittently
heap-corrupts (glibc `malloc_consolidate` SIGABRT, or a SIGSEGV — often at
interpreter teardown) in compiled `Simulation`/`HybridSimulation` runs; the
seed tier-1 shows the same DOTS_PASSED=0 / rc=134 signature, and CHANGES.md
PR 1-3 env notes re-verified it on unmodified HEAD. An in-process abort
kills the whole pytest run, so every test that drives a compiled Simulation
runs its device legs in a SUBPROCESS through this helper and SKIPS (never
silently passes) when the corruption signature appears. Engine-harness
tests stay in-process — those paths are stable here and remain the primary
gates.

Usage:
    from tests.subproc import run_isolated, run_isolated_json

    proc = run_isolated(SCRIPT, arg1, arg2)      # skips on the signature,
    assert proc.returncode == 0, proc.stderr     # else a normal proc
    data = run_isolated_json(SCRIPT, arg1)       # + parses last stdout line
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import pytest

# the corruption-signature taxonomy lives in ONE place now
# (tools/corruption.py; docs/corruption.md is the prose companion) —
# the rc set stays re-exported here for existing importers
from tools.corruption import (  # noqa: F401  (re-export)
    HEAP_CORRUPTION_RCS,
    classify as classify_corruption,
)

_REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))

# this box's sitecustomize registers the axon TPU plugin and forces
# jax_platforms="axon,cpu", overriding the JAX_PLATFORMS env var — the
# prelude pins the backend back the way tests/conftest.py does
_PRELUDE = "import jax\njax.config.update('jax_platforms', 'cpu')\n"

# native_plane_skip_reason() memo: None = not probed yet, "" = usable,
# anything else = the skip reason
_NATIVE_PROBE: str | None = None

# the shim exits 97 when its IPC handshake never delivers MSG_START —
# the native plane could BUILD but cannot LOAD/attach in this
# environment (seccomp/SIGSYS or ptrace-adjacent container policy).
# native_plane.py uses 97 for exactly this (see _die(97) call sites).
SHIM_LOAD_FAILURE_RC = 97


def native_plane_skip_reason(retries: int = 1) -> str | None:
    """Environment classification for tests driving REAL binaries under
    the native shim. Returns None when the plane is usable, else a
    skip reason (attempt-reporting, same posture as run_isolated):

      - the toolchain did not build -> the classic "unavailable" skip;
      - the toolchain built but a trivial probe process exits 97 (the
        shim's MSG_START handshake never arrived — containers whose
        seccomp/namespace policy blocks the shim's attach) -> skip with
        the probe evidence, instead of every real-binary leg hard-F'ing
        on exit_code/output asserts and reading as a regression.

    Any OTHER probe failure returns None: a broken-but-loadable shim is
    a real bug the tests themselves must surface, not an environment to
    classify away. The probe runs once per process (memoized) and only
    when a caller asks — modules skip on it at collection, so unrelated
    test runs never pay it."""
    global _NATIVE_PROBE
    if _NATIVE_PROBE is not None:
        return _NATIVE_PROBE or None
    from shadow_tpu.native_plane import ensure_built, spawn_native

    if not ensure_built():
        _NATIVE_PROBE = "native toolchain unavailable"
        return _NATIVE_PROBE
    from shadow_tpu.host import CpuHost, HostConfig
    from shadow_tpu.host.network import CpuNetwork

    attempts = []
    for _attempt in range(retries + 1):
        hs = [CpuHost(HostConfig(
            name="shimprobe", ip="10.99.0.1", seed=1, host_id=0
        ))]
        net = CpuNetwork(hs, latency_ns=lambda s, d: 10_000_000)
        p = spawn_native(hs[0], ["/bin/sh", "-c", "echo shim-probe-ok"])
        try:
            net.run(2_000_000_000)
        finally:
            for h in hs:
                h.shutdown()
        out = b"".join(p.stdout)
        if p.exit_code != SHIM_LOAD_FAILURE_RC:
            # usable (exit 0) or broken in a way the real tests must
            # report loudly — either way, do not classify it away
            _NATIVE_PROBE = ""
            return None
        attempts.append(
            f"exit={p.exit_code} out={out[:40]!r}"
        )
    _NATIVE_PROBE = (
        f"native shim cannot load in this container: "
        f"{len(attempts)}/{len(attempts)} probe processes died with the "
        f"exit-97 MSG_START-handshake signature ({'; '.join(attempts)}) "
        f"— real-binary legs would hard-F on environment, not code"
    )
    return _NATIVE_PROBE


def classify_deviation(observations: list) -> str | None:
    """Deviation classification for same-seed subprocess runs that MUST
    agree: returns the documented WRONG-DIGEST corruption flavor when
    the observations vary, else None ("they agree — judge the values").

    The silent flavor of this box's jaxlib-0.4.37 corruption scribbles
    device state mid-flight and the run still exits 0 with a wrong
    result — only detectable by comparison (tools/corruption.py
    WRONG_DIGEST). A test whose legs are same-seed deterministic by
    the engine's own gates (tests/test_determinism.py) therefore treats
    cross-run disagreement as the environment striking a worker, not as
    a verdict: retry, and if every attempt deviates, skip through
    `skip_deviation` with the evidence — never hard-fail tier-1 on it
    (test_integrity's driver drill flaked exactly this way on
    unmodified HEAD during PR 12's wave)."""
    from tools.corruption import WRONG_DIGEST

    if len({repr(o) for o in observations}) > 1:
        return WRONG_DIGEST
    return None


def skip_deviation(what: str, attempts: int, evidence) -> None:
    """Skip (never silently pass, never hard-fail) a test whose
    same-seed legs kept deviating after retries — the attempt-reporting
    posture `run_isolated` uses for the loud corruption flavors,
    extended to the comparison-judged WRONG-DIGEST flavor."""
    from tools.corruption import WRONG_DIGEST

    pytest.skip(
        f"{what}: same-seed runs deviated in {attempts}/{attempts} "
        f"attempts (the {WRONG_DIGEST} flavor of the documented "
        f"jaxlib-0.4.37 corruption, tools/corruption.py — environment, "
        f"not a verdict): {evidence}"
    )


def run_isolated(
    script: str, *argv: str, timeout: int = 600, prelude: bool = True,
    retries: int = 1,
) -> subprocess.CompletedProcess:
    """Run `script` via `python -c` in a clean subprocess (repo on
    PYTHONPATH, CPU backend pinned, the conftest's 8-virtual-device
    XLA_FLAGS inherited so `world > 1` legs still see a mesh). Calls
    `pytest.skip` when the run dies with the known heap-corruption
    signature AND produced no stdout — a real assertion failure (rc 1,
    stdout present) is never masked.

    The corruption is INTERMITTENT (a one-off malloc_consolidate abort can
    hit a run that would pass on the next try), so the signature — and a
    subprocess timeout, its hang flavor — gets `retries` fresh attempts
    (default one) before skipping; the skip reason reports how many
    attempts died so a systematically-failing leg is distinguishable from
    a one-off. `timeout` bounds TOTAL wall across all attempts (retries
    run on the remaining budget): an abort dies fast and retries with
    nearly the whole budget, while a hang consumes it in one attempt and
    skips — a retried hang must never double the leg's worst case past
    check_tier1.sh's whole-stage timeout."""
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        PYTHONPATH=os.pathsep.join([_REPO, os.environ.get("PYTHONPATH", "")]),
    )
    cmd = [sys.executable, "-c", (_PRELUDE if prelude else "") + script,
           *[str(a) for a in argv]]
    attempts = retries + 1
    deadline = time.monotonic() + timeout
    for attempt in range(1, attempts + 1):
        remaining = deadline - time.monotonic()
        try:
            proc = subprocess.run(
                cmd, capture_output=True, text=True,
                timeout=max(remaining, 1), env=env, cwd=_REPO,
            )
        except subprocess.TimeoutExpired as e:
            if attempt <= retries and deadline - time.monotonic() > 1:
                continue
            # same no-masking guard as the rc path: a child that printed
            # something before hanging got far enough that the hang is
            # plausibly a real deadlock regression — re-raise (visible
            # error) instead of skipping it away. Only a silent hang
            # matches the corruption's profile (these scripts print a
            # single result line at the very end); classify() applies
            # exactly that output guard.
            flavor = classify_corruption(
                timed_out=True, output=e.stdout or b""
            )
            if flavor is None:
                raise
            pytest.skip(
                f"isolated subprocess timed out (attempt {attempt}, "
                f"{timeout}s total budget) with no output (the "
                f"{flavor} flavor of the known jaxlib-0.4.37 "
                f"corruption, tools/corruption.py): "
                f"{(e.stderr or b'')[-200:]!r}"
            )
        flavor = classify_corruption(proc.returncode, output=proc.stdout)
        if flavor is not None:
            if attempt <= retries:
                continue  # one-off abort: retry before skipping
            pytest.skip(
                "known jaxlib-0.4.37 heap corruption in compiled Simulation "
                f"runs on this box ({flavor} flavor, tools/corruption.py), "
                f"{attempts}/{attempts} attempts died (CHANGES.md env "
                f"notes): {proc.stderr[-200:]}"
            )
        return proc


def run_isolated_json(
    script: str, *argv: str, timeout: int = 600
) -> dict:
    """`run_isolated` + assert rc == 0 + parse the LAST stdout line as
    JSON (scripts print their result dict last; progress chatter above is
    fine). A crash AFTER the result line — the teardown-time flavor of
    the corruption — still yields the result: the run itself completed."""
    proc = run_isolated(script, *argv, timeout=timeout)
    lines = proc.stdout.strip().splitlines()
    if proc.returncode in HEAP_CORRUPTION_RCS and lines:
        try:
            # completed-then-crashed-at-exit: the printed result is valid
            return json.loads(lines[-1])
        except ValueError:
            # crashed MID-print: a truncated result line is still the
            # corruption signature, not a test failure
            pytest.skip(
                "known heap corruption truncated the subprocess result "
                f"(rc={proc.returncode}): {proc.stderr[-200:]}"
            )
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert lines, f"script printed no result line; stderr: {proc.stderr}"
    return json.loads(lines[-1])
