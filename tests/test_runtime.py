"""Runtime observatory (`observability.runtime`, shadow_tpu/obs/runtime.py).

Gates, mirroring the ISSUE acceptance:
  - observer exactness: digests, event counts, and drop counters are
    bit-identical with the compile ledger attached vs not, across
    echo/phold/tgen x flat/bucketed x K{1,4} (engine harness, in
    process) plus a world-8 subprocess leg (tests/subproc.py, this
    box's documented jaxlib-0.4.37 corruption posture);
  - compile-ledger correctness: exactly one cold_start entry per jitted
    program, cache hits counted per later call, and — against a forced
    pressure regrow (Simulation, escalate policy, undersized capacity)
    — each new (gear, capacity, budget) rung is exactly one recorded
    compile carrying the pressure_regrow trigger, reconciled against
    the engine's own program caches;
  - WallLedger exactness: per-chunk span sums equal the chunk wall by
    construction (host_python is the residual), reattribution moves
    seconds without double-counting, and the realtime-factor series
    tracks sim-s/wall-s;
  - BridgeTelemetry: lanes sum to the window wall (bridge is the
    residual) and the syscall-batch histogram counts every batch;
  - heartbeat `rt=` strict round-trip through parse_shadow;
  - bench helpers: post_compile_stats (the shared compile-chunk
    exclusion rule) and bench_runtime_block's diffable shape;
  - rt_report CLI smoke on a real run's artifacts (tests/subproc.py).

Engine-harness legs run in-process (the stable path on this box);
compiled-Simulation legs go through tests/subproc.py."""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import jax
import numpy as np
import pytest

from shadow_tpu.core import Engine
from shadow_tpu.obs.runtime import (
    INJECT_HIST_EDGES_S,
    SPAN_NAMES,
    BridgeTelemetry,
    CompileLedger,
    WallLedger,
    assemble_runtime_report,
    bench_runtime_block,
    span_or_null,
)
from tests.engine_harness import build_sim, mk_hosts

_REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


# ---------------------------------------------------------------------------
# WallLedger: per-chunk exactness + reattribution
# ---------------------------------------------------------------------------


def test_wall_ledger_chunk_spans_sum_to_chunk_wall():
    w = WallLedger()
    w.sync_sim(0)
    w.chunk_start()
    with w.span("dispatch"):
        time.sleep(0.02)
    with w.span("export"):
        time.sleep(0.01)
    time.sleep(0.01)  # uncovered -> host_python residual
    rt = w.chunk_end(3_000_000_000)
    assert rt is not None and rt > 0
    assert len(w.chunks) == 1
    c = w.chunks[0]
    # exactness by construction: residual is folded into host_python
    assert abs(sum(c["spans"].values()) - c["wall_s"]) < 1e-9
    assert c["spans"]["host_python"] > 0
    assert c["sim_ns"] == 3_000_000_000
    # totals mirror the single chunk
    assert abs(sum(w.totals.values()) - c["wall_s"]) < 1e-9
    # rt = sim seconds / wall seconds
    assert rt == pytest.approx(3.0 / c["wall_s"], rel=1e-6)


def test_wall_ledger_reattribute_moves_without_double_count():
    w = WallLedger()
    w.sync_sim(0)
    w.chunk_start()
    with w.span("dispatch"):
        time.sleep(0.03)
    w.reattribute("dispatch", "compile", 0.01)
    assert w.pending_to("compile") == pytest.approx(0.01)
    # a move larger than the source's balance clamps, never goes negative
    w.reattribute("dispatch", "snapshot", 10.0)
    w.chunk_end(1_000_000_000)
    c = w.chunks[0]
    assert c["spans"]["compile"] == pytest.approx(0.01, abs=1e-6)
    assert c["spans"].get("dispatch", 0.0) >= 0.0
    assert abs(sum(c["spans"].values()) - c["wall_s"]) < 1e-9


def test_wall_ledger_sync_sim_resets_rt_baseline():
    w = WallLedger()
    w.sync_sim(5_000_000_000)  # restored run: pre-restore horizon
    w.chunk_start()
    time.sleep(0.001)
    rt = w.chunk_end(5_000_000_000 + 1_000_000)
    # credited only with the post-sync delta, not the 5 s horizon
    assert rt == pytest.approx(0.001 / w.chunks[0]["wall_s"], rel=1e-6)


def test_wall_ledger_bounded_records():
    w = WallLedger(max_chunks=2)
    for i in range(5):
        w.chunk_start()
        w.chunk_end(i * 1_000_000)
    assert len(w.chunks) == 2
    assert w.chunks_total == 5 and w.chunks_dropped == 3
    s = w.summary()
    assert s["chunks"] == 5 and s["chunks_recorded"] == 2


def test_span_or_null_without_ledger():
    with span_or_null(None, "dispatch"):
        pass  # must be a usable nullcontext
    w = WallLedger()
    w.chunk_start()
    with span_or_null(w, "dispatch"):
        pass
    w.chunk_end(0)
    assert w.chunks_total == 1


# ---------------------------------------------------------------------------
# CompileLedger: cold-call recording + cache hits + window filter
# ---------------------------------------------------------------------------


def test_compile_ledger_records_cold_call_then_hits():
    led = CompileLedger()
    calls = []

    def fn(x):
        calls.append(x)
        time.sleep(0.005)
        return x * 2

    wrapped = led.instrument("chunk", "base", "cold_start", fn)
    assert wrapped(3) == 6
    assert wrapped(4) == 8
    assert wrapped(5) == 10
    assert calls == [3, 4, 5]  # arguments/results pass through untouched
    assert len(led.entries) == 1
    e = led.entries[0]
    assert (e["kind"], e["label"], e["trigger"]) == (
        "chunk", "base", "cold_start"
    )
    assert e["cold_s"] >= 0.005
    assert e["hits"] == 2 and led.cache_hits == 2
    s = led.summary()
    assert s["programs"] == 1 and s["by_trigger"] == {"cold_start": 1}
    assert s["cold_wall_s"] > 0


def test_compile_ledger_window_filter_and_wall_reattribution():
    wall = WallLedger()
    led = CompileLedger(wall=wall)
    t_before = time.monotonic()
    wrapped = led.instrument("chunk", "rung", "pressure_regrow",
                             lambda: time.sleep(0.002))
    wall.chunk_start()
    with wall.span("dispatch"):
        wrapped()
    wall.chunk_end(1_000_000)
    e = led.entries[0]
    # the cold call started inside [t_before, now) and outside a
    # disjoint window
    assert led.compiles_in(t_before, time.monotonic()) == pytest.approx(
        led.pipeline_s(e)
    )
    assert led.compiles_in(t_before - 100, t_before - 50) == 0.0
    ev = led.events()
    assert len(ev) == 1 and ev[0][0] == "chunk:rung (pressure_regrow)"
    assert ev[0][2] > 0


# ---------------------------------------------------------------------------
# BridgeTelemetry: lane exactness + syscall-batch histogram
# ---------------------------------------------------------------------------


def test_bridge_telemetry_window_lanes_sum_to_wall():
    bt = BridgeTelemetry()
    bt.sync_sim(0)
    bt.window_start()
    bt.note("cpu_plane", 0.002)
    bt.note("device_plane", 0.003)
    time.sleep(0.01)
    rt = bt.window_end(2_000_000_000)
    assert rt is not None and rt > 0
    w = bt.windows[0]
    lanes = w["cpu_plane"] + w["device_plane"] + w["bridge"]
    assert lanes == pytest.approx(w["wall_s"], abs=1e-9)
    assert w["bridge"] > 0  # the residual landed in the bridge lane


def test_bridge_telemetry_batch_histogram_counts_every_batch():
    bt = BridgeTelemetry()
    bt.window_start()
    lat = [5e-5, 2e-4, 2e-3, 0.05, 10.0]  # first + overflow buckets
    for i, sec in enumerate(lat):
        bt.note_batch(sec, entries=i + 1)
    bt.window_end(0)
    s = bt.summary()
    b = s["syscall_batches"]
    assert b["batches"] == len(lat)
    assert b["entries"] == sum(range(1, len(lat) + 1))
    assert sum(b["hist_counts"]) == len(lat)
    assert len(b["hist_counts"]) == len(INJECT_HIST_EDGES_S) + 1
    assert b["hist_counts"][0] == 1          # 5e-5 <= 1e-4
    assert b["hist_counts"][-1] == 1         # 10 s -> +inf bucket
    assert b["wall_s"] == pytest.approx(sum(lat), abs=1e-3)
    assert set(s["shares"]) == set(BridgeTelemetry.LANES)
    assert sum(s["shares"].values()) == pytest.approx(1.0, abs=1e-3)


# ---------------------------------------------------------------------------
# report assembly + bench helpers
# ---------------------------------------------------------------------------


def test_assemble_runtime_report_shapes():
    wall = WallLedger()
    wall.chunk_start()
    time.sleep(0.001)
    wall.chunk_end(1_000_000_000)
    led = CompileLedger()
    led.instrument("chunk", "base", "cold_start", lambda: None)()
    rep = assemble_runtime_report(
        wall=wall, compiles=led, total_wall_s=wall.chunks[0]["wall_s"]
    )
    assert set(rep["spans_s"]) == set(SPAN_NAMES)
    assert rep["chunks"] == 1
    assert 0.9 <= rep["attributed_share"] <= 1.01
    assert rep["realtime_factor"]["series"]
    assert rep["compiles"]["programs"] == 1
    # bridge-only assembly (the hybrid driver's shape) still carries a
    # realtime factor, derived from the windows
    bt = BridgeTelemetry()
    bt.window_start()
    time.sleep(0.001)
    bt.window_end(500_000_000)
    rep2 = assemble_runtime_report(bridge=bt)
    assert rep2["bridge"]["windows"] == 1
    assert rep2["realtime_factor"]["last"] > 0


def test_post_compile_stats_shared_exclusion_rule():
    from bench import post_compile_stats

    # normal shape: walls[0] carries the compile, its chunk's rounds are
    # excluded with it
    wall, rounds = post_compile_stats([5.0, 1.0, 1.0], 300, rpc=64,
                                      replicas=1)
    assert wall == pytest.approx(2.0) and rounds == 300 - 64
    # replicas scale the excluded rounds
    wall, rounds = post_compile_stats([5.0, 1.0], 300, rpc=32, replicas=4)
    assert wall == pytest.approx(1.0) and rounds == 300 - 32 * 4
    # whole run fit inside the compile chunk: that chunk IS the
    # measurement
    wall, rounds = post_compile_stats([5.0], 100, rpc=64, replicas=1)
    assert wall == pytest.approx(5.0) and rounds == 100
    # rounds-free variant (bench --self measure path)
    wall, rounds = post_compile_stats([5.0, 2.0])
    assert wall == pytest.approx(2.0) and rounds is None


def test_bench_runtime_block_shape():
    led = CompileLedger()
    t0 = time.monotonic()
    led.instrument("chunk", "base", "cold_start",
                   lambda: time.sleep(0.002))()
    t1 = time.monotonic()
    blk = bench_runtime_block(led, None, sim_adv_s=10.0, wall_s=2.0,
                              window=(t0, t1))
    assert blk["realtime_factor"] == pytest.approx(5.0)
    assert blk["compile_programs"] == 1
    assert blk["compile_in_window_s"] >= 0
    # excluding the in-window compile can only raise the factor
    assert blk["realtime_factor_ex_compile"] >= blk["realtime_factor"]


def test_bench_compare_runtime_block():
    """The runtime{} diff gate (unit-gated like the hbm/network/fluid
    gates): realtime-factor drop or compile-wall growth beyond
    tolerance = regression, lost block = coverage warning, sub-second
    compile-wall noise never regresses."""
    sys.path.insert(0, _REPO)
    from tools.bench_compare import _rows, compare

    def row(rt, cw):
        return {"metric": "m", "value": 10.0, "runtime": {
            "realtime_factor": rt, "compile_wall_s": cw,
            "realtime_factor_ex_compile": rt, "compile_programs": 3,
        }}

    old = _rows([row(4.0, 10.0)])
    # regression: rt -50%, compile wall +50% (and > 1 s absolute)
    findings = compare(old, _rows([row(2.0, 15.0)]), 0.10, 0.10)
    det = " | ".join(f["detail"] for f in findings
                     if f["kind"] == "runtime")
    kinds = {(f["kind"], f["severity"]) for f in findings}
    assert ("runtime", "regression") in kinds
    assert "realtime factor" in det and "compile wall" in det
    # improvement is reported, not a regression
    findings = compare(old, _rows([row(8.0, 10.0)]), 0.10, 0.10)
    assert any(f["kind"] == "runtime" and f["severity"] == "improvement"
               for f in findings)
    assert not any(f["kind"] == "runtime" and f["severity"] == "regression"
                   for f in findings)
    # sub-second compile growth never regresses even at a big ratio
    old_small = _rows([row(4.0, 0.2)])
    findings = compare(old_small, _rows([row(4.0, 0.9)]), 0.10, 0.10)
    assert not any(f["kind"] == "runtime" for f in findings)
    # identical blocks: silent
    assert not [f for f in compare(old, _rows([row(4.0, 10.0)]),
                                   0.1, 0.1) if f["kind"] == "runtime"]
    # losing the block entirely is a coverage warning
    findings = compare(old, _rows([{"metric": "m", "value": 10.0}]),
                       0.1, 0.1)
    assert any(f["kind"] == "runtime" and f["severity"] == "warning"
               for f in findings)
    # sim-stats-shaped realtime_factor dicts compare through `overall`
    dict_rt = {"metric": "m", "value": 10.0, "runtime": {
        "realtime_factor": {"overall": 2.0, "p50": 2.1},
        "compile_wall_s": 10.0,
    }}
    findings = compare(old, _rows([dict_rt]), 0.10, 0.10)
    assert any(f["kind"] == "runtime" and f["severity"] == "regression"
               and "realtime factor" in f["detail"] for f in findings)


# ---------------------------------------------------------------------------
# heartbeat rt= strict round-trip
# ---------------------------------------------------------------------------


def test_heartbeat_rt_strict_roundtrip(tmp_path):
    from shadow_tpu.sim import heartbeat_line
    from tools.parse_shadow import parse_heartbeats

    lines = [
        heartbeat_line(2_000_000_000, 3.0, 99, 80, 40, 4096, 7, rt=4.42),
        heartbeat_line(2_000_000_000, 3.0, 99, 80, 40, 4096, 7,
                       gear=4, cap=32, hbm=12345, iv=(0, 0), rt=0.07),
        heartbeat_line(2_000_000_000, 3.0, 99, 80, 40, 4096, 7),
    ]
    p = tmp_path / "hb.log"
    p.write_text("\n".join(lines) + "\n")
    parsed = parse_heartbeats(str(p), strict=True)
    assert parsed[0]["rt"] == pytest.approx(4.42)
    assert parsed[1]["rt"] == pytest.approx(0.07)
    assert parsed[1]["cap"] == 32 and parsed[1]["hbm"] == 12345
    assert "rt" not in parsed[2]


# ---------------------------------------------------------------------------
# config surface
# ---------------------------------------------------------------------------


def test_observability_runtime_knob_parses():
    from shadow_tpu.config.options import ObservabilityOptions

    assert not ObservabilityOptions.from_dict({}).runtime  # default off
    assert ObservabilityOptions.from_dict({"runtime": True}).runtime


def test_example_runtime_yaml_parses():
    from shadow_tpu.config.options import load_config

    cfg = load_config(os.path.join(_REPO, "examples", "runtime.yaml"))
    assert cfg.observability.runtime
    assert cfg.observability.trace
    assert cfg.pressure.active and cfg.pressure.policy == "escalate"


# ---------------------------------------------------------------------------
# observer exactness matrix (engine harness, world=1)
# ---------------------------------------------------------------------------

RING = 64

_CASES = {
    "phold": ("phold", mk_hosts(8, {"mean_delay": "20 ms", "population": 3}),
              300_000_000, dict(loss=0.1)),
    "echo": ("udp_echo",
             [dict(host_id=0, name="server", start_time=0,
                   model_args={"role": "server"})]
             + [dict(host_id=i, name=f"c{i}", start_time=0,
                     model_args={"role": "client", "peer": "server",
                                 "interval": "4 ms", "size_bytes": 2000})
                for i in range(1, 5)],
             200_000_000, dict(bw_bits=2_000_000, loss=0.05)),
    "tgen": ("tgen_tcp",
             mk_hosts(5, {"flow_segs": 8, "flows": 2, "cwnd_cap": 8,
                          "rto_min": "100 ms"}),
             2_000_000_000,
             dict(loss=0.05, latency=10_000_000, sends_budget=16)),
}


def _run(model, hosts, stop, *, k=1, qb=0, ledger=None, **kw):
    cfg, m, params, mstate, events = build_sim(
        model, hosts, stop, world=1, queue_block=qb, microstep_events=k,
        **kw
    )
    eng = Engine(cfg, m, None)
    if ledger is not None:
        eng.attach_compile_ledger(ledger)
    state, params = eng.init_state(params, mstate, events, seed=1)
    chunks = 0
    while not bool(state.done):
        state = eng.run_chunk(state, params)
        chunks += 1
        assert chunks < 500
    return state, chunks


def _matrix_params():
    """The world-1 acceptance matrix (netobs posture): the mixed-axis
    combos — (flat, k4) and (bucketed, k1), which add no code path the
    aligned pairs miss for a purely host-side wrapper — carry the `slow`
    mark so the FULL cross product runs under `pytest -m ''` while
    tier-1 runs the aligned half plus the world-8 leg."""
    out = []
    for case in sorted(_CASES):
        for k in (1, 4):
            for qb in (0, 8):
                aligned = (k == 1) == (qb == 0)
                marks = () if aligned else (pytest.mark.slow,)
                out.append(pytest.param(
                    case, k, qb,
                    id=f"{case}-{'flat' if qb == 0 else 'bucketed'}-k{k}",
                    marks=marks,
                ))
    return out


@pytest.mark.parametrize("case,k,qb", _matrix_params())
def test_runtime_observer_is_bit_identical(case, k, qb):
    """The ISSUE acceptance gate, world=1: the compile ledger attached
    vs not across the model x layout x K matrix — digests, event counts,
    and drop counters bit-identical (the observatory wraps jitted
    callables host-side; the traced program cannot change), and the
    ledger records exactly the one base program with every later chunk
    a cache hit."""
    model, hosts, stop, kw = _CASES[case]
    s_off, _ = _run(model, hosts, stop, k=k, qb=qb, **kw)
    led = CompileLedger()
    s_on, chunks = _run(model, hosts, stop, k=k, qb=qb, ledger=led, **kw)
    off, on = jax.device_get(s_off.stats), jax.device_get(s_on.stats)

    np.testing.assert_array_equal(np.asarray(off.digest),
                                  np.asarray(on.digest))
    np.testing.assert_array_equal(np.asarray(off.events),
                                  np.asarray(on.events))
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(s_off.queue.dropped)),
        np.asarray(jax.device_get(s_on.queue.dropped)),
    )
    assert len(led.entries) == 1  # one jitted base program
    e = led.entries[0]
    assert e["trigger"] == "cold_start"
    assert e["hits"] == chunks - 1  # every later chunk hit the cache
    assert e["cold_s"] > 0


def test_compile_ledger_gear_variant_is_one_entry():
    """A gear-shifted chunk compiles once per gear width: exactly one
    gear_shift entry on first use, cache hits after."""
    model, hosts, stop, kw = _CASES["phold"]
    cfg, m, params, mstate, events = build_sim(
        model, hosts, stop, world=1, **kw)
    eng = Engine(cfg, m, None)
    led = CompileLedger()
    eng.attach_compile_ledger(led)
    state, params = eng.init_state(params, mstate, events, seed=1)
    state = eng.run_chunk(state, params)
    gear = max(1, cfg.sends_per_host_round // 2)
    state = eng.run_chunk_gear(state, params, gear)
    state = eng.run_chunk_gear(state, params, gear)
    trig = {(e["trigger"], e["label"]): e["hits"] for e in led.entries}
    assert trig[("cold_start", "base")] == 0
    assert trig[("gear_shift", f"gear={gear}")] == 1
    assert len(led.entries) == 2


# world=8 leg (subprocess-isolated: compiled multi-device runs are where
# this box's documented corruption bites — tests/subproc.py)
_W8_SCRIPT = """
import json
import numpy as np
import jax
from shadow_tpu.core import Engine
from shadow_tpu.obs.runtime import CompileLedger
from tests.engine_harness import build_sim, mk_hosts

hosts = mk_hosts(8, {"mean_delay": "20 ms", "population": 3})

def run(ledger):
    cfg, m, params, mstate, events = build_sim(
        "phold", hosts, 300_000_000, world=8, loss=0.1)
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:8]), ("hosts",))
    eng = Engine(cfg, m, mesh)
    if ledger is not None:
        eng.attach_compile_ledger(ledger)
    state, params = eng.init_state(params, mstate, events, seed=1)
    chunks = 0
    while not bool(state.done):
        state = eng.run_chunk(state, params)
        chunks += 1
        assert chunks < 500
    return state, chunks

s_off, _ = run(None)
led = CompileLedger()
s_on, chunks = run(led)
off, on = jax.device_get(s_off.stats), jax.device_get(s_on.stats)
print(json.dumps({
    "digest_equal": bool(
        (np.asarray(off.digest) == np.asarray(on.digest)).all()),
    "events_equal": bool(
        (np.asarray(off.events) == np.asarray(on.events)).all()),
    "dropped_equal": bool((
        np.asarray(jax.device_get(s_off.queue.dropped))
        == np.asarray(jax.device_get(s_on.queue.dropped))).all()),
    "programs": len(led.entries),
    "hits": led.entries[0]["hits"],
    "chunks": chunks,
}))
"""


def test_runtime_observer_world8_subprocess():
    from tests.subproc import run_isolated_json

    out = run_isolated_json(_W8_SCRIPT, timeout=600)
    assert out["digest_equal"] and out["events_equal"]
    assert out["dropped_equal"]
    assert out["programs"] == 1
    assert out["hits"] == out["chunks"] - 1


# ---------------------------------------------------------------------------
# Simulation leg: forced pressure regrow — new rung == one recorded
# compile — plus the runtime{} block, rt= heartbeat, and compile track
# ---------------------------------------------------------------------------

_SIM_WORKER = '''
import io, json, os, sys
import numpy as np
from shadow_tpu.config.options import ConfigOptions
from shadow_tpu.sim import Simulation

rt_on = sys.argv[1] == "on"
tmp = sys.argv[2]
cfg = ConfigOptions.from_dict({
    "general": {"stop_time": "3 s", "seed": 1,
                "heartbeat_interval": "1 s",
                "data_directory": tmp},
    "network": {"graph": {"type": "1_gbit_switch"}},
    "experimental": {"event_queue_capacity": 8,
                     "rounds_per_chunk": 8},
    "observability": {"trace": True, "runtime": rt_on},
    "pressure": {"policy": "escalate", "max_capacity": 64},
    "hosts": {"n": {"count": 16, "network_node_id": 0,
              "processes": [{"model": "phold",
                             "model_args": {"population": 6,
                                            "mean_delay": "100 ms"}}]}},
})
log = io.StringIO()
sim = Simulation(cfg, world=1)
r = sim.run(progress=False, log=log)
sim.write_outputs(report=r)
hb = [l for l in log.getvalue().splitlines() if "[heartbeat]" in l]
out = {
    "digest": r["determinism_digest"],
    "events": r["events_processed"],
    "drops": [r["queue_overflow_dropped"],
              r["packets_budget_dropped"], r["packets_lost"]],
    "regrows": r.get("pressure_regrows", 0),
    "heartbeat": hb[0] if hb else "",
    "has_runtime": "runtime" in r,
    "resized_cached": len(sim.engine._resized_chunks),
    "gear_cached": len(sim.engine._gear_chunks),
}
if rt_on:
    rt = r["runtime"]
    out["rt_block"] = {
        "chunks": rt.get("chunks"),
        "attributed_share": rt.get("attributed_share"),
        "series_len": len((rt.get("realtime_factor") or {})
                          .get("series") or []),
        "spans": sorted((rt.get("spans_s") or {}).keys()),
    }
    out["compiles"] = rt["compiles"]
    trace = json.load(open(os.path.join(tmp, "trace.json")))
    out["compile_track"] = len([e for e in trace["traceEvents"]
                                if e.get("cat") == "compile"])
print(json.dumps(out))
'''


def test_simulation_runtime_on_off_and_pressure_regrow_ledger(tmp_path):
    """Full-driver leg: observability.runtime on vs off on a scenario
    whose undersized queue forces REAL pressure regrows — digests/
    events/drops bit-identical, and the compile ledger records exactly
    the programs the (gear, capacity, budget) cache compiled: one
    cold_start plus one pressure_regrow entry per cached rung."""
    from tests.subproc import run_isolated_json

    on = run_isolated_json(
        _SIM_WORKER, "on", str(tmp_path / "rt_on"), timeout=600
    )
    off = run_isolated_json(
        _SIM_WORKER, "off", str(tmp_path / "rt_off"), timeout=600
    )
    assert on["digest"] == off["digest"]
    assert on["events"] == off["events"]
    assert on["drops"] == off["drops"]
    assert not off["has_runtime"]

    # the scenario really regrew (otherwise the ledger gate is vacuous)
    assert on["regrows"] > 0 and on["resized_cached"] > 0

    comp = on["compiles"]
    expect = 1 + on["gear_cached"] + on["resized_cached"]
    assert comp["programs"] == expect
    assert comp["by_trigger"]["cold_start"] == 1
    # each new rung = exactly one recorded compile
    assert comp["by_trigger"]["pressure_regrow"] == on["resized_cached"]
    assert comp["compile_wall_s"] > 0
    assert comp["cache_hits"] > 0

    # attribution plane: block present, spans cover the wall
    blk = on["rt_block"]
    assert blk["chunks"] > 0 and blk["series_len"] > 0
    assert blk["attributed_share"] is not None
    assert 0.85 <= blk["attributed_share"] <= 1.01
    assert blk["spans"] == sorted(SPAN_NAMES)

    # Chrome-trace compile track: one X event per recorded program
    assert on["compile_track"] == comp["programs"]

    # live rt= heartbeat, strict-parsed through the format gate
    from tools.parse_shadow import HEARTBEAT_RE

    assert "rt=" in on["heartbeat"]
    assert "rt=" not in off["heartbeat"]
    m = HEARTBEAT_RE.search(on["heartbeat"])
    assert m and float(m.group("rt")) >= 0

    # rt_report CLI smoke on the run's real artifacts (report mode
    # imports no JAX — safe in a plain subprocess)
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "rt_report.py"),
         str(tmp_path / "rt_on")],
        capture_output=True, text=True, timeout=120, cwd=_REPO,
    )
    assert proc.returncode == 0, proc.stderr
    assert "runtime observatory report" in proc.stdout
    assert "compile ledger" in proc.stdout
    assert "verdict" in proc.stdout
    proc_j = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "rt_report.py"),
         str(tmp_path / "rt_on"), "--json"],
        capture_output=True, text=True, timeout=120, cwd=_REPO,
    )
    assert proc_j.returncode == 0, proc_j.stderr
    blob = json.loads(proc_j.stdout)
    assert blob["compiles"]["programs"] == comp["programs"]


# ---------------------------------------------------------------------------
# hybrid leg: bridge split + rt= in the windows-form heartbeat
# ---------------------------------------------------------------------------

_HYBRID_WORKER = '''
import io, json, sys
from shadow_tpu.config.options import ConfigOptions
from shadow_tpu.cosim import HybridSimulation

rt_on = sys.argv[1] == "on"
cfg = ConfigOptions.from_dict({
    "general": {"stop_time": "2 s", "seed": 7,
                "heartbeat_interval": "500 ms"},
    "network": {"graph": {"type": "1_gbit_switch"}},
    "observability": {"runtime": rt_on},
    "hosts": {
        "server": {"network_node_id": 0,
                   "processes": [{"path": "udp_echo_server",
                                  "args": ["port=9000"]}]},
        "client": {"network_node_id": 0,
                   "processes": [{"path": "udp_ping",
                                  "args": ["server=server", "port=9000",
                                           "count=3"],
                                  "expected_final_state": {"exited": 0}}]},
    },
})
log = io.StringIO()
sim = HybridSimulation(cfg)
r = sim.run(log=log)
hb = [l for l in log.getvalue().splitlines() if "[heartbeat]" in l]
print(json.dumps({
    "digest": r["determinism_digest"],
    "delivered": r["packets_delivered"],
    "failures": r["process_failures"],
    "heartbeat": hb[0] if hb else "",
    "runtime": r.get("runtime"),
}))
'''


def test_hybrid_bridge_split_on_off():
    """The cosim driver's observatory leg: per-window bridge-stall split
    present and internally consistent with the observatory on, digest
    identical to the off run."""
    from tests.subproc import run_isolated_json

    on = run_isolated_json(_HYBRID_WORKER, "on", timeout=420)
    off = run_isolated_json(_HYBRID_WORKER, "off", timeout=420)
    assert on["failures"] == 0 and off["failures"] == 0
    assert on["digest"] == off["digest"]
    assert on["delivered"] == off["delivered"]
    rt = on["runtime"]
    assert rt is not None and off["runtime"] is None
    br = rt["bridge"]
    assert br["windows"] > 0
    assert set(br["spans_s"]) == {"cpu_plane", "device_plane", "bridge"}
    b = br["syscall_batches"]
    assert b["batches"] > 0
    assert sum(b["hist_counts"]) == b["batches"]
    # shares sum to ~1 and the compile ledger saw the bridge's programs
    assert sum(br["shares"].values()) == pytest.approx(1.0, abs=1e-2)
    assert rt["compiles"]["programs"] >= 2  # prepare + guarded
    assert rt["realtime_factor"]["last"] > 0
    if on["heartbeat"]:  # windows-form heartbeat carries rt=
        assert "rt=" in on["heartbeat"]
        from tools.parse_shadow import HEARTBEAT_RE

        assert HEARTBEAT_RE.search(on["heartbeat"])
        assert "rt=" not in off["heartbeat"]
