"""Two-level bucketed event queue: equivalence + cache-coherence gate.

The `BucketQueue` contract (ops/events.py) is *bit-identical behavior* to the
flat `EventQueue` — same popped events, same written slots, same drop
counters — with per-block (min-time, min-order, fill) caches maintained
incrementally on pop/push and rebuilt wholesale only at the exchange merge
and checkpoint restore. These tests are the determinism gate for that claim:

  1. a property test drives random interleavings of pop / push / merge
     through both queue types (and both backend formulations of each op)
     and asserts slabs, events, drops, and the block-min invariant after
     every single operation;
  2. a regression test for the nastiest incremental case: pop empties a
     block, a push refills it, the next pop must see the refreshed cache;
  3. engine-level runs of echo, phold, and tgen produce bit-identical
     per-host digests for flat vs two different block sizes (the ISSUE's
     acceptance gate, CPU backend);
  4. checkpoint round-trip of a bucketed sim resumes identically (restore
     is a cache-rebuild point).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from shadow_tpu.ops import (
    as_flat,
    block_minima,
    bq_pop_min,
    bq_push_many,
    bucket_rebuild,
    make_bucket_queue,
    make_queue,
    merge_flat_events,
    next_time,
    bq_next_time,
    pack_order,
    pop_min,
    push_many,
)
from shadow_tpu.ops.events import EVENT_PAYLOAD_WORDS
from shadow_tpu.simtime import TIME_MAX

from tests.engine_harness import mk_hosts, run_sim


def assert_caches_coherent(bq, msg=""):
    """The block-min invariant: caches == wholesale recompute from the slab."""
    nb = bq.bt.shape[1]
    bt, bo, bfill = block_minima(bq.t, bq.order, nb)
    np.testing.assert_array_equal(np.asarray(bq.bt), np.asarray(bt), err_msg=f"bt {msg}")
    np.testing.assert_array_equal(np.asarray(bq.bo), np.asarray(bo), err_msg=f"bo {msg}")
    np.testing.assert_array_equal(
        np.asarray(bq.bfill), np.asarray(bfill), err_msg=f"bfill {msg}"
    )


def assert_queues_equal(qf, bq, msg=""):
    ff = as_flat(bq)
    for fa, fb, name in zip(qf, ff, qf._fields):
        np.testing.assert_array_equal(
            np.asarray(fa), np.asarray(fb), err_msg=f"{name} {msg}"
        )


# ------------------------------------------------------------------ property


@pytest.mark.parametrize("path", ["gather", "onehot"])
@pytest.mark.parametrize("block", [2, 4, 8])
def test_random_ops_bit_identical_to_flat(block, path):
    """Random pop/push/merge interleavings: flat and bucketed queues must
    stay bit-identical (slabs, events, active masks, drop counters) and the
    block caches must satisfy the block-min invariant after EVERY op —
    across block sizes and both backend formulations of pop/push."""
    hh, cc = 6, 8
    rng = np.random.default_rng(block * 100 + (path == "onehot"))
    qf = make_queue(hh, cc)
    bq = make_bucket_queue(hh, cc, block)
    seq = 0
    for step in range(60):
        op = rng.choice(["push", "pop", "merge"], p=[0.45, 0.35, 0.2])
        msg = f"step {step} op {op} block {block} path {path}"
        if op == "push":
            k = int(rng.integers(1, 4))
            pushes = []
            for _ in range(k):
                mask = jnp.asarray(rng.random(hh) < 0.7)
                t = jnp.asarray(rng.integers(1, 1000, hh), jnp.int64)
                order = jnp.asarray(
                    [int(pack_order(1, i, seq + 7 * i)) for i in range(hh)],
                    jnp.int64,
                )
                seq += 1
                kind = jnp.asarray(rng.integers(0, 5, hh), jnp.int32)
                payload = jnp.asarray(
                    rng.integers(0, 99, (hh, EVENT_PAYLOAD_WORDS)), jnp.int32
                )
                pushes.append((mask, t, order, kind, payload))
            qf = push_many(qf, pushes)
            bq = bq_push_many(bq, pushes, force_path=path)
        elif op == "pop":
            limit = int(rng.choice([TIME_MAX, 500, 50]))
            qf, evf, af = pop_min(qf, limit)
            bq, evb, ab = bq_pop_min(bq, limit, force_path=path)
            np.testing.assert_array_equal(np.asarray(af), np.asarray(ab), err_msg=msg)
            for fa, fb, name in zip(evf, evb, evf._fields):
                np.testing.assert_array_equal(
                    np.asarray(fa), np.asarray(fb), err_msg=f"ev.{name} {msg}"
                )
            np.testing.assert_array_equal(
                np.asarray(next_time(qf)), np.asarray(bq_next_time(bq)), err_msg=msg
            )
        else:
            n = int(rng.integers(1, 12))
            dst = jnp.asarray(rng.integers(0, hh, n), jnp.int32)
            t = jnp.asarray(rng.integers(1, 1000, n), jnp.int64)
            order = jnp.asarray(
                [int(pack_order(0, int(rng.integers(0, hh)), 5000 + seq + i))
                 for i in range(n)],
                jnp.int64,
            )
            seq += n
            kind = jnp.asarray(rng.integers(0, 5, n), jnp.int32)
            payload = jnp.asarray(
                rng.integers(0, 99, (n, EVENT_PAYLOAD_WORDS)), jnp.int32
            )
            valid = jnp.asarray(rng.random(n) < 0.8)
            qf = merge_flat_events(
                qf, dst, t, order, kind, payload, valid, max_inserts=cc
            )
            bq = merge_flat_events(
                bq, dst, t, order, kind, payload, valid, max_inserts=cc
            )
        assert_queues_equal(qf, bq, msg)
        assert_caches_coherent(bq, msg)


# ---------------------------------------------------------------- regression


@pytest.mark.parametrize("path", ["gather", "onehot"])
def test_pop_after_push_into_popped_empty_block(path):
    """Popping a block empty, pushing into it, then popping again must see
    the refreshed cache: the pop's incremental recompute has to clear the
    victim block's minimum, and the push's 2-way update has to resurrect it
    — a stale cache either replays the popped event or hides the new one."""
    bq = make_bucket_queue(1, 4, 2)
    one = jnp.ones((1,), bool)

    def push(q, t, seq):
        return bq_push_many(
            q,
            [(one, jnp.asarray([t], jnp.int64),
              jnp.asarray([int(pack_order(1, 0, seq))], jnp.int64),
              jnp.asarray([1], jnp.int32),
              jnp.zeros((1, EVENT_PAYLOAD_WORDS), jnp.int32))],
            force_path=path,
        )

    # fill block 0 (slots 0-1) and one slot of block 1
    bq = push(bq, 10, 0)
    bq = push(bq, 20, 1)
    bq = push(bq, 30, 2)  # lands in block 1
    # drain block 0
    bq, ev, active = bq_pop_min(bq, TIME_MAX, force_path=path)
    assert int(ev.t[0]) == 10 and bool(active[0])
    bq, ev, _ = bq_pop_min(bq, TIME_MAX, force_path=path)
    assert int(ev.t[0]) == 20
    assert_caches_coherent(bq, "after draining block 0")
    assert int(bq.bfill[0, 0]) == 0 and int(bq.bt[0, 0]) == TIME_MAX
    # push into the popped-empty block (first-free slot is in block 0)
    bq = push(bq, 5, 3)
    assert_caches_coherent(bq, "after refilling block 0")
    assert int(bq.bt[0, 0]) == 5
    # next pops must order across the refreshed block-0 cache and block 1
    bq, ev, _ = bq_pop_min(bq, TIME_MAX, force_path=path)
    assert int(ev.t[0]) == 5
    bq, ev, _ = bq_pop_min(bq, TIME_MAX, force_path=path)
    assert int(ev.t[0]) == 30
    bq, _, active = bq_pop_min(bq, TIME_MAX, force_path=path)
    assert not bool(active[0])
    assert_caches_coherent(bq, "after draining everything")


def test_rebuild_rejects_bad_block():
    q = make_queue(2, 8)
    with pytest.raises(ValueError):
        bucket_rebuild(q, 3)  # does not divide capacity
    with pytest.raises(ValueError):
        bucket_rebuild(q, 0)


def test_degenerate_block_equals_capacity():
    """B=C (one block) is the flat queue with a cache bolted on — it must
    still behave identically."""
    bq = make_bucket_queue(2, 4, 4)
    qf = make_queue(2, 4)
    mask = jnp.asarray([True, True])
    push = [(mask, jnp.asarray([7, 3], jnp.int64),
             jnp.asarray([int(pack_order(1, 0, 0)), int(pack_order(1, 1, 0))],
                         jnp.int64),
             jnp.asarray([1, 1], jnp.int32),
             jnp.zeros((2, EVENT_PAYLOAD_WORDS), jnp.int32))]
    qf = push_many(qf, push)
    bq = bq_push_many(bq, push)
    assert_queues_equal(qf, bq)
    assert_caches_coherent(bq)
    qf, evf, _ = pop_min(qf, TIME_MAX)
    bq, evb, _ = bq_pop_min(bq, TIME_MAX)
    np.testing.assert_array_equal(np.asarray(evf.t), np.asarray(evb.t))
    assert_queues_equal(qf, bq)


# ------------------------------------------------------- engine determinism


def _run(model, hosts, stop, qb, **kw):
    _, stats, _ = run_sim(model, hosts, stop, world=1, queue_block=qb, **kw)
    return stats


@pytest.mark.parametrize(
    "model,hosts,stop,kw",
    [
        ("phold", mk_hosts(10, {"mean_delay": "20 ms", "population": 2}),
         400_000_000, dict(loss=0.1)),
        ("udp_echo",
         [dict(host_id=0, name="server", start_time=0,
               model_args={"role": "server"})]
         + [dict(host_id=i, name=f"c{i}", start_time=0,
                 model_args={"role": "client", "peer": "server",
                             "interval": "4 ms", "size_bytes": 2000})
            for i in range(1, 5)],
         300_000_000, dict(bw_bits=2_000_000, loss=0.05, use_codel=True)),
        ("tgen_tcp",
         mk_hosts(6, {"flow_segs": 12, "flows": 1, "cwnd_cap": 8,
                      "rto_min": "100 ms"}),
         4_000_000_000, dict(loss=0.05, latency=10_000_000, sends_budget=16)),
    ],
    ids=["phold", "echo", "tgen_tcp"],
)
def test_engine_digest_flat_vs_bucketed(model, hosts, stop, kw):
    """The ISSUE acceptance gate: per-host event digests bit-identical
    between the flat queue and the bucketed queue on echo, phold, and tgen
    workloads (same seed, CPU backend), across TWO different block sizes."""
    s_flat = _run(model, hosts, stop, 0, **kw)
    for qb in (8, 16):  # harness queue capacity is 32: C/B = 4 and 2
        s_b = _run(model, hosts, stop, qb, **kw)
        np.testing.assert_array_equal(
            np.asarray(s_flat.digest), np.asarray(s_b.digest),
            err_msg=f"{model} block={qb}",
        )
        assert int(np.asarray(s_flat.events).sum()) == int(
            np.asarray(s_b.events).sum()
        )
        # bucketed runs actually rebuilt caches at exchanges (sanity that
        # the two-level path was exercised, not silently flat)
        assert int(np.asarray(s_b.bq_rebuilds).sum()) > 0


# ----------------------------------------------------------------- restore


def test_checkpoint_roundtrip_bucketed(tmp_path):
    """Checkpoint restore is a cache-rebuild point: a bucketed sim resumed
    from a snapshot must finish with the same digest as an uninterrupted
    run. A different BLOCK size is a capacity shape since the pressure
    plane's cross-capacity restore (PR 8): the load migrates and the
    resumed run still matches; only a layout-KIND change (bucketed ->
    flat) refuses. Runs in a subprocess (tests/subproc.py): this is a
    compiled-Simulation leg, the shape that intermittently heap-corrupts
    in-process on this box."""
    from tests.subproc import run_isolated_json

    out = run_isolated_json('''
import json, sys
import numpy as np
from shadow_tpu.config.options import ConfigOptions
from shadow_tpu.core.checkpoint import (
    CheckpointError,
    load_checkpoint,
    save_checkpoint,
)
from shadow_tpu.ops import as_flat, block_minima
from shadow_tpu.sim import Simulation
from shadow_tpu.simtime import TIME_MAX


def cfg(block=4):
    return ConfigOptions.from_dict({
        "general": {"stop_time": "4 s", "seed": 17},
        "network": {"graph": {"type": "1_gbit_switch"}},
        "experimental": {"event_queue_capacity": 16,
                         "event_queue_block": block},
        "hosts": {
            "n": {
                "count": 8,
                "network_node_id": 0,
                "processes": [{
                    "model": "phold",
                    "model_args": {"population": 2,
                                   "mean_delay": "100 ms"},
                }],
            }
        },
    })


a = Simulation(cfg(), world=1)
a.run(progress=False)
digest_a = a.stats_report()["determinism_digest"]

b = Simulation(cfg(), world=1)
b.state = b.engine.run_chunk(b.state, b.params)
assert not bool(b.state.done)
ckpt = sys.argv[1]
save_checkpoint(ckpt, b)

c = Simulation(cfg(), world=1)
load_checkpoint(ckpt, c)
# restored caches must match a from-scratch rebuild (the in-process
# assert_caches_coherent helper, inlined here)
q = c.state.queue
bt, bo, bfill = block_minima(q.t, q.order, q.bt.shape[1])
assert (np.asarray(q.bt) == np.asarray(bt)).all()
assert (np.asarray(q.bo) == np.asarray(bo)).all()
assert (np.asarray(q.bfill) == np.asarray(bfill)).all()
c.run(progress=False)
digest_c = c.stats_report()["determinism_digest"]

# a different BLOCK size migrates (capacity shape, PR 8) and the resumed
# run must still land on the uninterrupted digest
d = Simulation(cfg(block=8), world=1)
load_checkpoint(ckpt, d)
d.run(progress=False)
digest_d = d.stats_report()["determinism_digest"]

# a layout-KIND change (bucketed checkpoint -> flat sim) refuses loudly
e = Simulation(cfg(block=0), world=1)
refused = False
try:
    load_checkpoint(ckpt, e)
except CheckpointError:
    refused = True
print(json.dumps({"digest_a": digest_a, "digest_c": digest_c,
                  "digest_d": digest_d, "refused": refused}))
''', str(tmp_path / "bq.npz"))
    assert out["digest_c"] == out["digest_a"]
    assert out["digest_d"] == out["digest_a"]
    assert out["refused"]
