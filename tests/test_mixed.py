"""Mixed simulations: device-modeled hosts + CPU-emulated hosts sharing one
device network (models/mixed.py). The flagship scenario: real clients load
a MODELED service at device scale — cross-plane echoes reconstruct exact
bytes; both planes ride the same latency/loss/exchange pipeline."""

from __future__ import annotations

import os

import pytest

from shadow_tpu.config.options import ConfigOptions
from shadow_tpu.cosim import HybridSimulation
from tests.subproc import native_plane_skip_reason

MS = 1_000_000
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# real-binary legs need the native shim to LOAD, not just build — the
# probe classifies the container-policy exit-97 signature into a skip
# with evidence instead of a hard F (tests/subproc.py)
_native_skip = native_plane_skip_reason()


def _cfg(client_procs, stop="4 s", seed=9, n_clients=3):
    return ConfigOptions.from_dict(
        {
            "general": {"stop_time": stop, "seed": seed},
            "network": {"graph": {"type": "1_gbit_switch"}},
            "hosts": {
                # the server is a DEVICE MODEL — no CPU process at all
                "server": {
                    "network_node_id": 0,
                    "processes": [
                        {"model": "udp_echo", "model_args": {"role": "server"}}
                    ],
                },
                "client": {
                    "network_node_id": 0,
                    "count": n_clients,
                    "processes": [client_procs],
                },
            },
        }
    )


def test_coroutine_clients_against_modeled_server():
    cfg = _cfg(
        {
            "path": "udp_ping",
            "args": ["server=server", "port=9000", "count=3"],
            "expected_final_state": {"exited": 0},
        }
    )
    sim = HybridSimulation(cfg, world=1)
    r = sim.run()
    assert r["process_failures"] == 0
    # every ping crossed to the model plane and back
    assert r["packets_delivered"] >= 3 * 3 * 2
    m = r["model_report"]["model_udp_echo"]
    assert m["requests_served"] == 9
    # the clients saw byte-exact echoes (udp_ping verifies content)
    outs = [
        b"".join(p.stdout)
        for h in sim.hosts
        for p in h.processes.values()
        if "ping" in p.name
    ]
    assert all(b"done" in o or b"rtt" in o for o in outs)


@pytest.mark.skipif(_native_skip is not None, reason=str(_native_skip))
def test_real_binary_against_modeled_server():
    """An UNMODIFIED real binary pings a host that exists only as a device
    model lane: simulated RTT is exact (2 x 1 ms switch latency)."""
    cfg = _cfg(
        {
            "path": os.path.join(REPO, "native", "build", "test_udp_client"),
            "args": ["11.0.0.4", "9000", "2"],
            "expected_final_state": {"exited": 0},
            "start_time": "100 ms",
        },
        n_clients=3,
    )
    sim = HybridSimulation(cfg, world=1)
    # IP sanity: hosts sort client1..client3, server -> server = 11.0.0.4
    assert {s.name: s.ip for s in sim.specs}["server"] == "11.0.0.4"
    r = sim.run()
    assert r["process_failures"] == 0, r
    out = b"".join(
        b"".join(p.stdout)
        for h in sim.hosts
        for p in h.processes.values()
    ).decode()
    # echo RTT == exactly 2 x 1 ms of SIMULATED time
    assert out.count("rtt_ns=2000000") == 6
    assert r["model_report"]["model_udp_echo"]["requests_served"] == 6


def test_native_traffic_does_not_forge_gossip_state():
    """Regression (r3 advisor): native-origin packets delivered to gossip
    lanes carried the bridge's byte-store key in payload word 2 and were
    adopted as spurious fresh generations, corrupting the flood state. The
    mixed-plane crossing now sanitizes native payload words (models/mixed.py)
    so foreign traffic counts as load, not protocol state."""
    cfg = ConfigOptions.from_dict(
        {
            "general": {"stop_time": "2 s", "seed": 3},
            "network": {"graph": {"type": "1_gbit_switch"}},
            "hosts": {
                "g": {
                    "count": 4,
                    "network_node_id": 0,
                    "processes": [
                        {"model": "gossip", "model_args": {"fanout": 2}}
                    ],
                },
                "blaster": {
                    "network_node_id": 0,
                    "processes": [
                        {
                            "path": "udp_blast",
                            "args": ["server=g1", "port=9000", "count=5"],
                            "expected_final_state": {"exited": 0},
                        }
                    ],
                },
            },
        }
    )
    sim = HybridSimulation(cfg, world=1)
    r = sim.run()
    assert r["process_failures"] == 0
    assert r["packets_delivered"] >= 5  # the blasts did cross planes
    m = r["model_report"]["model_gossip"]
    # no publisher in this sim: native packets must not mint generations
    assert m["generations"] == 0
    assert m["adoptions"] == 0


def test_mixed_two_runs_identical():
    def once():
        cfg = _cfg(
            {
                "path": "udp_ping",
                "args": ["server=server", "port=9000", "count=2"],
                "expected_final_state": {"exited": 0},
            },
            seed=4,
        )
        sim = HybridSimulation(cfg, world=1)
        r = sim.run()
        return (r["determinism_digest"], r["packets_sent"],
                r["packets_delivered"], r["events_processed"])

    assert once() == once()


def test_mixed_mesh_invariant():
    def once(world):
        cfg = _cfg(
            {
                "path": "udp_ping",
                "args": ["server=server", "port=9000", "count=2"],
                "expected_final_state": {"exited": 0},
            },
            seed=6,
        )
        sim = HybridSimulation(cfg, world=world)
        r = sim.run()
        return (r["determinism_digest"], r["packets_delivered"])

    assert once(1) == once(8)


def test_mixed_inner_model_mesh_invariant():
    """Regression (r3 review): the inner model must be built over the REAL
    lanes and zero-padded — building at the padded width would hand phold a
    world-dependent num_hosts (pad lanes receiving and re-spraying jobs),
    diverging digests across mesh sizes."""

    def once(world):
        cfg = ConfigOptions.from_dict(
            {
                "general": {"stop_time": "2 s", "seed": 5},
                "network": {"graph": {"type": "1_gbit_switch"}},
                "hosts": {
                    "m": {
                        "count": 4,
                        "network_node_id": 0,
                        "processes": [{
                            "model": "phold",
                            "model_args": {"population": 1,
                                           "mean_delay": "100 ms"},
                        }],
                    },
                    "real": {
                        "network_node_id": 0,
                        "processes": [{
                            "path": "udp_echo_server",
                            "args": ["port=9000"],
                        }],
                    },
                },
            }
        )
        sim = HybridSimulation(cfg, world=world)
        r = sim.run()
        return (r["determinism_digest"], r["events_processed"],
                r["packets_sent"])

    r1 = once(1)
    r8 = once(8)
    assert r1 == r8
    assert r1[1] > 4  # the modeled plane actually churned
