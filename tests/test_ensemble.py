"""Ensemble plane gates (core/ensemble.py + tools/campaign.py).

The ISSUE acceptance property: replica r of a vmapped campaign is
BIT-IDENTICAL — digest, event count, every drop/fault counter — to a solo
run with the same (seed, fault schedule), across echo/phold/tgen x
flat/bucketed queues x K in {1, 4}; plus a forced-divergence campaign
whose bisection must report the correct first divergent chunk.

In-process legs stick to single-dispatch engine-harness runs (the stable
path on this box); multi-chunk legs (bisection, the campaign driver) run
through tests/subproc.py — this box's documented jaxlib-0.4.37 corruption
targets exactly the many-small-dispatch pattern they need (CHANGES.md env
notes), and an in-process abort would kill the whole pytest run.

Build-order note: each replica is built ONCE; the ensemble stacks COPIES
of the per-replica states (jnp.stack allocates), so the same build then
runs its solo leg afterwards — solo dispatches donate only their own
state buffers, never the stacked ones.
"""

from __future__ import annotations

import os
import sys

import jax
import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(__file__))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from engine_harness import build_sim, mk_hosts  # noqa: E402
from tests.subproc import run_isolated_json  # noqa: E402

from shadow_tpu.config.options import ConfigError, ConfigOptions  # noqa: E402
from shadow_tpu.core import Engine  # noqa: E402
from shadow_tpu.core.ensemble import (  # noqa: E402
    EnsembleEngine,
    build_ensemble,
    pair_digests_equal,
    replica_digest_sigs,
    replica_ledger,
    tree_index,
)

# the counters the bit-identity gate compares, per replica vs solo
_GATED_STATS = (
    "digest", "events", "pkts_sent", "pkts_lost", "pkts_delivered",
    "pkts_unreachable", "pkts_codel_dropped", "pkts_budget_dropped",
    "monotonic_violations", "faults_dropped", "faults_delayed",
    "popk_deferred",
)


def _build_replica(model_name, hosts, stop, *, seed, faults=None, **kw):
    """One replica's (engine, model, (cfg, state, params))."""
    cfg, m, params, mstate, events = build_sim(
        model_name, hosts, stop, world=1, seed=seed, faults=faults, **kw
    )
    eng = Engine(cfg, m, None)
    state, params = eng.init_state(params, mstate, events, seed=seed)
    return eng, m, (eng.cfg, state, params)


def _run_solo(eng, state, params, max_chunks=200):
    n = 0
    while not bool(state.done):
        state = eng.run_chunk(state, params)
        n += 1
        assert n < max_chunks, "solo run failed to terminate"
    return state


def _run_ensemble(ens, state, max_chunks=200):
    n = 0
    while not bool(np.asarray(jax.device_get(state.done)).all()):
        state = ens.run_chunk(state)
        n += 1
        assert n < max_chunks, "ensemble run failed to terminate"
    return state, n


def _build_and_run(model_name, hosts, stop, specs, **common_kw):
    """Build replicas from (seed, faults) specs, stack + run the ensemble,
    then run each build's solo leg. Returns (ens, ens_state, solo_states)."""
    builds = [
        _build_replica(model_name, hosts, stop, seed=seed, faults=faults,
                       **common_kw)
        for seed, faults in specs
    ]
    model = builds[0][1]
    ens, state = build_ensemble(model, [rep for _, _, rep in builds])
    state, _ = _run_ensemble(ens, state)
    solos = [
        _run_solo(eng, rep[1], rep[2]) for eng, _, rep in builds
    ]
    return ens, state, solos


def _assert_replica_matches_solo(ens_state, r, solo_state, ctx=""):
    es = jax.device_get(ens_state.stats)
    ss = jax.device_get(solo_state.stats)
    for f in _GATED_STATS:
        a = np.asarray(getattr(es, f))[r]
        b = np.asarray(getattr(ss, f))
        np.testing.assert_array_equal(
            a, b, err_msg=f"{ctx} replica {r}: stats.{f} diverged from solo"
        )
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(ens_state.queue.dropped))[r],
        np.asarray(jax.device_get(solo_state.queue.dropped)),
        err_msg=f"{ctx} replica {r}: queue.dropped diverged from solo",
    )
    assert int(np.asarray(jax.device_get(ens_state.stats.rounds))[r]) == int(
        solo_state.stats.rounds
    ), f"{ctx} replica {r}: rounds diverged from solo"


# the three workloads of the acceptance grid (the test_popk _CASES shapes:
# phold's bursty pushes exercise the K-fold deferral guard, echo the
# shaping pipeline, tgen the TCP plane)
_CASES = {
    "phold": (
        "phold",
        mk_hosts(10, {"mean_delay": "20 ms", "population": 3}),
        400_000_000,
        dict(loss=0.1),
    ),
    "echo": (
        "udp_echo",
        [dict(host_id=0, name="server", start_time=0,
              model_args={"role": "server"})]
        + [dict(host_id=i, name=f"c{i}", start_time=0,
                model_args={"role": "client", "peer": "server",
                            "interval": "4 ms", "size_bytes": 2000})
           for i in range(1, 5)],
        300_000_000,
        dict(bw_bits=2_000_000, loss=0.05, use_codel=True),
    ),
    "tgen": (
        "tgen_tcp",
        mk_hosts(6, {"flow_segs": 12, "flows": 1, "cwnd_cap": 8,
                     "rto_min": "100 ms"}),
        4_000_000_000,
        dict(loss=0.05, latency=10_000_000, sends_budget=16),
    ),
}

# queue layout x K-fold grid; qb (queue_block) must divide the harness
# qcap of 32
_GRID = [(0, 1), (0, 4), (8, 1), (8, 4)]


@pytest.mark.parametrize("case", sorted(_CASES))
@pytest.mark.parametrize("qb,k", _GRID, ids=lambda v: str(v))
def test_vmap_vs_solo_bit_identity(case, qb, k):
    """THE acceptance gate: every replica of a seed-sweep ensemble equals
    its solo run bit-for-bit, across models x queue layouts x K."""
    model_name, hosts, stop, kw = _CASES[case]
    _, state, solos = _build_and_run(
        model_name, hosts, stop, [(s, None) for s in (1, 2, 3)],
        queue_block=qb, microstep_events=k, **kw,
    )
    for r, solo_state in enumerate(solos):
        _assert_replica_matches_solo(
            state, r, solo_state, ctx=f"{case} qb={qb} k={k}"
        )


def test_vmap_vs_solo_fault_schedule_sweep():
    """Fault-schedule axis: replicas with DIFFERENT schedules (different
    window counts — exercises the crash-window padding — plus loss
    windows on every replica per the mixing rule) each equal their
    natural solo runs, which compile the UNPADDED dims."""
    hosts = mk_hosts(8, {"mean_delay": "20 ms", "population": 3})
    stop = 400_000_000
    scheds = [
        {"crashes": [{"host": 2, "down_at": "0.1 s", "up_at": "0.25 s"},
                     {"host": 2, "down_at": "0.3 s", "up_at": "0.35 s"}],
         "loss_windows": [{"start": "0.05 s", "end": "0.2 s", "loss": 0.3}]},
        {"host_churn": {"prob": 0.5, "mean_downtime": "0.1 s"}, "seed": 9,
         "loss_windows": [{"start": "0.1 s", "end": "0.3 s", "loss": 0.1,
                           "latency_factor": 2.0},
                          {"start": "0.32 s", "end": "0.36 s",
                           "loss": 0.5}]},
    ]
    ens, state, solos = _build_and_run(
        "phold", hosts, stop,
        [(1, scheds[0]), (2, scheds[1]), (3, scheds[0])],
        loss=0.1,
    )
    # the reconciled statics are the maxima over the sweep
    assert ens.cfg.fault_crash_windows >= 1
    assert ens.cfg.fault_loss_windows == 2
    for r, solo_state in enumerate(solos):
        _assert_replica_matches_solo(state, r, solo_state, ctx="fault-sweep")
    # fault-plane sanity: the schedules really did something
    assert np.asarray(jax.device_get(state.stats.faults_delayed)).sum() > 0


def test_crash_pad_zero_to_w_exact():
    """A fault-free replica stacked with a crashing one: the 0 -> W crash
    padding must leave the fault-free replica bit-identical to its
    schedule-free solo build (no loss windows anywhere, so the mixing
    rule does not bite)."""
    hosts = mk_hosts(6, {"mean_delay": "20 ms", "population": 2})
    stop = 300_000_000
    crash = {"crashes": [{"host": 1, "down_at": "0.1 s", "up_at": "0.2 s"}]}
    ens, state, solos = _build_and_run(
        "phold", hosts, stop, [(1, None), (1, crash)]
    )
    assert ens.cfg.fault_crash_windows == 1
    for r, solo_state in enumerate(solos):
        _assert_replica_matches_solo(state, r, solo_state, ctx="pad0W")
    # and the two replicas did diverge (the crash held events)
    assert not pair_digests_equal(state, (0, 1))


def test_clear_policy_pads_fault_free_replica():
    """A restart_queue: clear crash replica stacked with a FAULT-FREE one
    must reconcile (the policy is value-inert for a host that is never
    down) — both replicas bit-identical to their solos — while two
    CRASHING replicas with different policies still reject."""
    hosts = mk_hosts(6, {"mean_delay": "20 ms", "population": 2})
    stop = 300_000_000
    clear = {"crashes": [{"host": 1, "down_at": "0.1 s", "up_at": "0.2 s"}],
             "restart_queue": "clear"}
    ens, state, solos = _build_and_run(
        "phold", hosts, stop, [(1, clear), (1, None)]
    )
    assert ens.cfg.fault_queue_clear and ens.cfg.fault_crash_windows == 1
    for r, solo_state in enumerate(solos):
        _assert_replica_matches_solo(state, r, solo_state, ctx="clear-pad")
    hold = {"crashes": [{"host": 1, "down_at": "0.1 s", "up_at": "0.2 s"}],
            "restart_queue": "hold"}
    _, model, rep_a = _build_replica("phold", hosts, stop, seed=1,
                                     faults=clear)
    _, _, rep_b = _build_replica("phold", hosts, stop, seed=1, faults=hold)
    with pytest.raises(ConfigError, match="restart_queue"):
        build_ensemble(model, [rep_a, rep_b])


def test_loss_window_mixing_rejected():
    """Mixing loss-window presence across replicas must fail loudly: L>0
    traces an extra RNG draw per send, so a fault-free replica could
    never match its solo run inside that program."""
    hosts = mk_hosts(4, {"mean_delay": "20 ms", "population": 2})
    stop = 200_000_000
    lossy = {"loss_windows": [{"start": "0.05 s", "end": "0.1 s",
                               "loss": 0.5}]}
    _, model, rep_a = _build_replica("phold", hosts, stop, seed=1)
    _, _, rep_b = _build_replica("phold", hosts, stop, seed=1, faults=lossy)
    with pytest.raises(ConfigError, match="loss-window"):
        build_ensemble(model, [rep_a, rep_b])


def test_static_mismatch_rejected():
    """Replicas differing in a trace-time static (here the K fold) must
    be rejected with the config-statics message."""
    hosts = mk_hosts(4, {"mean_delay": "20 ms", "population": 2})
    stop = 200_000_000
    _, model, rep_a = _build_replica(
        "phold", hosts, stop, seed=1, microstep_events=1
    )
    _, _, rep_b = _build_replica(
        "phold", hosts, stop, seed=2, microstep_events=4
    )
    with pytest.raises(ConfigError, match="EngineConfig static"):
        build_ensemble(model, [rep_a, rep_b])


def test_world_gt_1_rejected():
    """The ensemble plane is world=1 this round — a mesh config raises."""
    import dataclasses

    cfg, model, *_ = build_sim(
        "phold", mk_hosts(8, {"mean_delay": "20 ms"}), 100_000_000, world=1
    )
    with pytest.raises(ConfigError, match="world"):
        EnsembleEngine(dataclasses.replace(cfg, world=8), model)


def test_identical_replicas_stay_identical():
    """The A/A control: two replicas built identically must end with
    equal digest arrays (and equal xor signatures)."""
    hosts = mk_hosts(6, {"mean_delay": "20 ms", "population": 2})
    stop = 300_000_000
    builds = [
        _build_replica("phold", hosts, stop, seed=1) for _ in range(2)
    ]
    ens, state = build_ensemble(builds[0][1], [rep for _, _, rep in builds])
    state, _ = _run_ensemble(ens, state)
    assert pair_digests_equal(state, (0, 1))
    sigs = replica_digest_sigs(state)
    assert sigs[0] == sigs[1]
    led = replica_ledger(state, labels=["a", "b"])
    assert led[0]["digest"] == led[1]["digest"]
    assert led[0]["events_processed"] == led[1]["events_processed"] > 0
    # tree_index extracts a coherent per-replica view
    sub = tree_index(state, 0)
    assert int(sub.stats.rounds) == led[0]["rounds"]


# ---------------------------------------------------------------- bisection

_BISECT_SCRIPT = r"""
import json, sys
sys.path.insert(0, "tests")
import jax, numpy as np
from engine_harness import build_sim, mk_hosts
from shadow_tpu.core import Engine
from shadow_tpu.core.checkpoint import snapshot_state
from shadow_tpu.core.ensemble import (
    bisect_divergence, build_ensemble, pair_digests_equal,
)

# same seed, two crash schedules: divergence starts in the chunk whose
# windows contain the 0.9 s crash. rounds_per_chunk=8 with 50 ms windows
# -> ~10 chunks over 4 sim-s, so the bisection genuinely bisects.
HOSTS = mk_hosts(8, {"mean_delay": "20 ms", "population": 2})
STOP = 4_000_000_000
SCHEDS = [
    {"crashes": [{"host": 1, "down_at": "0.9 s", "up_at": "1.2 s"}]},
    {"crashes": [{"host": 1, "down_at": "2.9 s", "up_at": "3.2 s"}]},
]
replicas, model = [], None
for sched in SCHEDS:
    cfg, model, params, mstate, events = build_sim(
        "phold", HOSTS, STOP, world=1, seed=1, faults=sched,
        rounds_per_chunk=8)
    eng = Engine(cfg, model, None)
    state, params = eng.init_state(params, mstate, events, seed=1)
    replicas.append((eng.cfg, state, params))
ens, state = build_ensemble(model, replicas)
snap0 = snapshot_state(state)

# ground truth by linear chunk scan on the full digest arrays
truth, chunks = None, 0
while not bool(np.asarray(jax.device_get(state.done)).all()):
    state = ens.run_chunk(state)
    chunks += 1
    assert chunks < 100
    if truth is None and not pair_digests_equal(state, (0, 1)):
        truth = chunks
assert truth is not None, "pair never diverged"
got = bisect_divergence(ens.run_chunk, snap0, (0, 1), hi=chunks)
print(json.dumps({"truth": truth, "bisected": got, "chunks": chunks}))
"""


def test_bisection_finds_first_divergent_chunk():
    """Forced divergence: an A/B pair differing only in WHEN a crash
    window opens must bisect to exactly the chunk a linear full-digest
    scan identifies. Multi-chunk dispatch pattern -> subprocess-isolated
    (the known corruption magnet; tests/subproc classifies it)."""
    data = run_isolated_json(_BISECT_SCRIPT, timeout=420)
    assert data["bisected"] == data["truth"], data
    # the 0.9 s crash lands mid-run, not in chunk 1: the search had a
    # real window to bisect
    assert 1 <= data["truth"] < data["chunks"], data


# ------------------------------------------------------- campaign driver

_CAMPAIGN_SCRIPT = r"""
import json, tempfile
from tools.campaign import _smoke_worker
with tempfile.TemporaryDirectory() as tmp:
    print(json.dumps(_smoke_worker(tmp)))
"""


@pytest.mark.slow
def test_campaign_driver_end_to_end():
    """tools/campaign.py end-to-end (subprocess-isolated): the A/A
    control holds, replica 0 equals its solo Simulation, and the forced
    A/B divergence bisects to the linear-scan chunk. Marked slow — the
    TIER1_CAMPAIGN=1 stage of check_tier1.sh runs the same body."""
    data = run_isolated_json(_CAMPAIGN_SCRIPT, timeout=500)
    assert data["ok"], data


def test_campaign_options_parse():
    base = {
        "general": {"stop_time": "1 s", "seed": 1},
        "hosts": {"n": {"count": 2, "network_node_id": 0,
                        "processes": [{"model": "timer",
                                       "model_args": {"interval": "100 ms"}}]}},
    }
    cfg = ConfigOptions.from_dict(
        {**base, "campaign": {"seeds": {"start": 5, "count": 3},
                              "overrides": [{}, {"general.seed": 9}],
                              "expect_identical": [[0, 1]]}}
    )
    assert cfg.campaign.seeds == [5, 6, 7]
    assert cfg.campaign.num_replicas == 6
    with pytest.raises(ConfigError, match="expect_identical"):
        ConfigOptions.from_dict(
            {**base, "campaign": {"seeds": [1], "expect_identical": [[0]]}}
        )
    with pytest.raises(ConfigError, match="max_replicas"):
        ConfigOptions.from_dict(
            {**base, "campaign": {"seeds": list(range(100))}}
        )
    with pytest.raises(ConfigError, match="supervisor"):
        ConfigOptions.from_dict(
            {**base, "campaign": {
                "seeds": [1],
                "fault_schedules": [
                    {"supervisor": {"snapshot_every_chunks": 2}}],
            }}
        )
    with pytest.raises(ConfigError, match="references a replica"):
        ConfigOptions.from_dict(
            {**base, "campaign": {"seeds": [1, 2],
                                  "expect_identical": [[0, 5]]}}
        )
    # the campaign block round-trips through to_dict (provenance dump)
    assert "campaign" in cfg.to_dict()


def test_campaign_replica_expansion_order():
    from tools.campaign import expand_replicas, replica_config_dict

    base = {
        "general": {"stop_time": "1 s", "seed": 42},
        "hosts": {"n": {"count": 2, "network_node_id": 0,
                        "processes": [{"model": "timer",
                                       "model_args": {"interval": "100 ms"}}]}},
        "campaign": {"seeds": [1, 2],
                     "fault_schedules": [{}, {"host_churn": {"prob": 0.1}}],
                     "overrides": [{}, {"general.seed": 7}]},
    }
    specs = expand_replicas(ConfigOptions.from_dict(base))
    assert len(specs) == 8
    # seed-major, then schedule, then override (the documented formula)
    assert [s.seed for s in specs[:4]] == [1, 1, 1, 1]
    assert specs[0].label == "seed=1,faults=0,ov=0"
    assert specs[2].faults == {"host_churn": {"prob": 0.1}}
    assert specs[4].seed == 2 and specs[4].faults == {}
    # overrides win over the seed axis where they collide (applied last)
    d = replica_config_dict(base, specs[1])
    assert d["general"]["seed"] == 7
    # deep dotted paths reach into host process lists
    from tools.campaign import _apply_dict_override

    _apply_dict_override(d, "hosts.n.processes.0.model_args.interval", "50 ms")
    assert d["hosts"]["n"]["processes"][0]["model_args"]["interval"] == "50 ms"
    # the campaign block never leaks into replica configs
    assert "campaign" not in d


# ------------------------------------------------------- satellites


def test_heartbeat_regex_rep_and_old_formats():
    """parse_shadow must read the new rep= field AND keep parsing every
    older line format verbatim (one literal line per generation — the
    same pattern as the gear= and faults= fields)."""
    from tools.parse_shadow import HEARTBEAT_RE

    camp = ("[heartbeat] sim_time=1.290s wall=1.63s events=574 rounds=72 "
            "msteps/round=2.5 ev/mstep=3.19 ici_bytes=0 q_hwm=7 "
            "rep=0/3 ratio=0.79x rss_gib=0.88")
    m = HEARTBEAT_RE.search(camp)
    assert m and m.group("rep_done") == "0" and m.group("rep_total") == "3"
    assert m.group("ratio") == "0.79"
    faulty_camp = ("[heartbeat] sim_time=1.293s wall=1.70s events=364 "
                   "rounds=48 msteps/round=2.4 ev/mstep=3.17 ici_bytes=0 "
                   "q_hwm=7 faults=0/4 rep=0/2 ratio=0.76x rss_gib=0.95")
    m = HEARTBEAT_RE.search(faulty_camp)
    assert m and m.group("rep_done") == "0" and m.group("rep_total") == "2"
    assert m.group("faults_dropped") == "0" and m.group("faults_delayed") == "4"
    # literal pre-ensemble formats, one per generation
    old_pr5 = ("[heartbeat] sim_time=1.043s wall=1.83s events=400 rounds=264 "
               "msteps/round=1.0 ev/mstep=1.44 ici_bytes=0 q_hwm=8 "
               "faults=20/38 ratio=0.57x rss_gib=0.85")
    m = HEARTBEAT_RE.search(old_pr5)
    assert m and m.group("rep_done") is None
    assert m.group("faults_dropped") == "20" and m.group("ratio") == "0.57"
    old_pr4 = ("[heartbeat] sim_time=1.000s wall=2.50s events=100 rounds=10 "
               "msteps/round=3.0 ev/mstep=3.33 ici_bytes=4096 q_hwm=7 "
               "gear=2 ratio=0.40x rss_gib=1.00")
    m = HEARTBEAT_RE.search(old_pr4)
    assert m and m.group("gear") == "2" and m.group("rep_done") is None
    old_pr2 = ("[heartbeat] sim_time=1.000s wall=2.50s events=100 rounds=10 "
               "msteps/round=3.0 ev/mstep=3.33 ratio=0.40x rss_gib=1.00")
    m = HEARTBEAT_RE.search(old_pr2)
    assert m and m.group("rep_done") is None and m.group("ratio") == "0.40"


def test_heartbeat_line_formats():
    """The factored formatter emits byte-stable lines (minus the live
    resource suffix) for every field combination, and they parse back."""
    from shadow_tpu.sim import heartbeat_line
    from tools.parse_shadow import HEARTBEAT_RE

    line = heartbeat_line(1_000_000_000, 2.5, 100, 30, 10, 4096, 7)
    assert line.startswith(
        "[heartbeat] sim_time=1.000s wall=2.50s events=100 rounds=10 "
        "msteps/round=3.0 ev/mstep=3.33 ici_bytes=4096 q_hwm=7 ratio=0.40x"
    )
    line = heartbeat_line(
        1_000_000_000, 2.5, 100, 30, 10, 0, 7,
        fault=(2, 3), gear=4, rep=(1, 8),
    )
    assert "faults=2/3 gear=4 rep=1/8 ratio=0.40x" in line
    m = HEARTBEAT_RE.search(line)
    assert m and m.group("rep_total") == "8" and m.group("gear") == "4"


def test_replica_tracer_folds_per_replica():
    """ReplicaTracer: per-replica cursors drain independently (a lagging
    replica's rows are not misattributed), totals split sums vs maxes,
    and wrap losses count per replica."""
    import jax.numpy as jnp

    from shadow_tpu.obs.tracer import (
        COL_EVENTS, COL_OCC_HWM, ReplicaTracer, TRACE_COLS, TraceRing,
    )

    rr, r_count = 4, 2
    rows = np.zeros((r_count, 1, rr, TRACE_COLS), np.int64)
    # replica 0 recorded 3 rounds (events 10, 20, 30; occ 5, 9, 2);
    # replica 1 recorded 1 round (events 7; occ 4)
    for i, (ev, occ) in enumerate([(10, 5), (20, 9), (30, 2)]):
        rows[0, 0, i, COL_EVENTS] = ev
        rows[0, 0, i, COL_OCC_HWM] = occ
    rows[1, 0, 0, COL_EVENTS] = 7
    rows[1, 0, 0, COL_OCC_HWM] = 4
    ring = TraceRing(rows=jnp.asarray(rows),
                     cursor=jnp.asarray([[3], [1]], jnp.int64))
    tr = ReplicaTracer(rr, r_count)
    assert tr.drain(ring) == 4
    t = tr.replica_totals()
    assert t[0]["rounds"] == 3 and t[0]["events"] == 60
    assert t[0]["occ_hwm"] == 9
    assert t[1]["rounds"] == 1 and t[1]["events"] == 7
    agg = tr.totals()
    assert agg["events"] == 67 and agg["occ_hwm"] == 9
    # second drain with only replica 1 advancing; its cursor jumped
    # 1 -> 6 over a 4-deep ring => 1 row lost, 4 folded
    rows2 = rows.copy()
    for i, ev in enumerate([100, 101, 102, 103]):
        rows2[1, 0, i, COL_EVENTS] = ev
    ring2 = TraceRing(rows=jnp.asarray(rows2),
                      cursor=jnp.asarray([[3], [6]], jnp.int64))
    assert tr.drain(ring2) == 4
    t = tr.replica_totals()
    assert t[0]["rounds"] == 3  # untouched
    # replica 1 now totals 5 folded rounds (1 + 4), 1 lost to the wrap
    assert t[1]["rounds"] == 5 and t[1]["rounds_lost"] == 1
    # rows folded in the second drain: cursors 2..5 -> ring idx 2, 3, 0, 1
    assert t[1]["events"] == 7 + 102 + 103 + 100 + 101
    assert int(tr.rounds.sum()) == 8


def test_ensemble_checkpoint_roundtrip_and_guard():
    """Replica-axis checkpoints: save/load round-trips a stacked state
    bit-exactly (bucket caches rebuilt per replica) and a wrong
    fingerprint refuses."""
    import tempfile

    from shadow_tpu.core.checkpoint import (
        CheckpointError,
        load_ensemble_checkpoint,
        save_ensemble_checkpoint,
        snapshot_state,
    )

    hosts = mk_hosts(6, {"mean_delay": "20 ms", "population": 2})
    stop = 300_000_000
    builds = [
        _build_replica("phold", hosts, stop, seed=seed, queue_block=8)
        for seed in (1, 2)
    ]
    ens, state = build_ensemble(builds[0][1], [rep for _, _, rep in builds])
    template = snapshot_state(state)
    state, _ = _run_ensemble(ens, state)
    with tempfile.TemporaryDirectory() as tmp:
        path = save_ensemble_checkpoint(
            os.path.join(tmp, "camp"), state, "fp-abc"
        )
        restored = load_ensemble_checkpoint(path, template, "fp-abc")
        for got, want in zip(
            jax.tree_util.tree_leaves(restored),
            jax.tree_util.tree_leaves(state),
        ):
            np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        with pytest.raises(CheckpointError, match="does not match"):
            load_ensemble_checkpoint(path, template, "fp-other")


def test_supervisor_sig_replica_aware():
    """state_digest_sig must accept an ensemble state ([R] rounds) —
    the campaign runs under the unmodified ChunkSupervisor."""
    from shadow_tpu.core.supervisor import state_digest_sig

    hosts = mk_hosts(4, {"mean_delay": "20 ms", "population": 2})
    builds = [
        _build_replica("phold", hosts, 200_000_000, seed=seed)
        for seed in (1, 2)
    ]
    ens, state = build_ensemble(builds[0][1], [rep for _, _, rep in builds])
    rounds, digest = state_digest_sig(state)
    assert rounds == 0 and isinstance(digest, int)
    state, _ = _run_ensemble(ens, state)
    rounds2, digest2 = state_digest_sig(state)
    assert rounds2 > 0 and digest2 != digest


def test_compat_shim_promoted():
    """The shard_map shim: one public home (core/compat.py), the old
    private engine alias intact, and cosim no longer imports engine
    privates at its two call sites."""
    from shadow_tpu.core import compat, engine

    assert engine._shard_map is compat.shard_map_compat
    src = open(os.path.join(
        os.path.dirname(__file__), "..", "shadow_tpu", "cosim.py"
    )).read()
    assert "from shadow_tpu.core.engine import _shard_map" not in src
    assert src.count("shard_map_compat") >= 2
