"""Config-level dual-scheduler gate (reference determinism test 2:
thread-per-host vs thread-per-core runs must byte-match; here tpu vs
cpu-reference — src/test/determinism/CMakeLists.txt:1-74)."""

from __future__ import annotations

import pytest

from shadow_tpu.config.options import ConfigError, ConfigOptions
from shadow_tpu.sim import Simulation


def _cfg(scheduler: str, extra_exp: dict | None = None):
    exp = {"scheduler": scheduler}
    exp.update(extra_exp or {})
    return ConfigOptions.from_dict(
        {
            "general": {"stop_time": "1 s", "seed": 9},
            "network": {"graph": {"type": "1_gbit_switch"}},
            "experimental": exp,
            "hosts": {
                "n": {
                    "count": 6,
                    "network_node_id": 0,
                    "processes": [
                        {
                            "model": "gossip",
                            # publisher: without it no host schedules a first
                            # event and the whole sim is vacuously empty
                            "model_args": {"fanout": 2, "publisher": True},
                        }
                    ],
                }
            },
        }
    )


def test_scheduler_choice_does_not_change_results(tmp_path):
    dev = Simulation(_cfg("tpu"), world=1)
    dev_report = dev.run(progress=False)
    gold = Simulation(_cfg("cpu-reference"), world=1)
    gold_report = gold.run(progress=False)
    assert (
        dev_report["determinism_digest"] == gold_report["determinism_digest"]
    )
    assert dev_report["events_processed"] > 0  # guard against a vacuous sim
    for k in ("events_processed", "packets_sent", "packets_delivered",
              "packets_lost", "rounds"):
        assert dev_report[k] == gold_report[k], k
    # outputs directory works for the golden path too
    out = gold.write_outputs(str(tmp_path / "gold"), report=gold_report)
    assert (tmp_path / "gold" / "hosts" / "n1" / "host-stats.json").exists()


def test_unknown_scheduler_rejected():
    with pytest.raises(ConfigError, match="scheduler"):
        _cfg("gpu")


def test_cpu_reference_accepts_cpu_delay():
    # the golden scheduler models the CPU busy horizon since round 2; it must
    # run cpu_delay configs and agree with the device engine (full parity is
    # covered by test_golden.py::test_cpu_delay_matches)
    gold = Simulation(_cfg("cpu-reference", {"cpu_delay": "1 ms"}), world=1)
    report = gold.run(progress=False)
    assert report["events_processed"] > 0
