"""Test harness: two TcpState endpoints over a simulated wire.

The reference's TCP crate tests drive `TcpState` pairs through mock
`Dependencies` (src/lib/tcp/src/tests/); this harness is the same idea in
simulated nanoseconds: an event list of in-flight segments with per-direction
latency, optional deterministic drop/reorder hooks, and timer servicing.
"""

from __future__ import annotations

import heapq
from typing import Callable

from shadow_tpu.tcp import Segment, TcpState

MS = 1_000_000


class Wire:
    def __init__(
        self,
        a: TcpState,
        b: TcpState,
        latency_ns: int = 10 * MS,
        drop: Callable[[int, str, Segment], bool] | None = None,
    ):
        self.ends = {"a": a, "b": b}
        self.latency = latency_ns
        self.drop = drop or (lambda i, d, s: False)
        self.now = 0
        self._q: list[tuple[int, int, str, Segment]] = []  # (t, uid, dst, seg)
        self._uid = 0
        self.sent: list[tuple[int, str, Segment]] = []  # full trace

    def _pump_output(self):
        for name, tcp in self.ends.items():
            dst = "b" if name == "a" else "a"
            for seg in tcp.poll_segments(self.now):
                idx = len(self.sent)
                self.sent.append((self.now, name, seg))
                if not self.drop(idx, name, seg):
                    self._uid += 1
                    heapq.heappush(
                        self._q, (self.now + self.latency, self._uid, dst, seg)
                    )

    def _next_time(self) -> int | None:
        cands = [self._q[0][0]] if self._q else []
        for tcp in self.ends.values():
            t = tcp.next_timer()
            if t is not None:
                cands.append(t)
        return min(cands) if cands else None

    def step(self) -> bool:
        """Advance to the next event; False when idle."""
        self._pump_output()
        t = self._next_time()
        if t is None:
            return False
        self.now = max(self.now, t)
        while self._q and self._q[0][0] <= self.now:
            _, _, dst, seg = heapq.heappop(self._q)
            self.ends[dst].on_segment(self.now, seg)
        for tcp in self.ends.values():
            tt = tcp.next_timer()
            if tt is not None and tt <= self.now:
                tcp.on_timer(self.now)
        self._pump_output()
        return True

    def run(self, max_steps: int = 10_000, until: Callable[[], bool] | None = None):
        for _ in range(max_steps):
            if until is not None and until():
                return
            if not self.step():
                if until is None or until():
                    return
        raise AssertionError(
            f"wire did not settle in {max_steps} steps (now={self.now})"
        )


def handshake(latency_ns: int = 10 * MS, **kw) -> tuple[TcpState, TcpState, Wire]:
    """Returns (client, server, wire) in ESTABLISHED. `cfg` sets both ends;
    `cfg_server` overrides the server side (asymmetric-option tests)."""
    from shadow_tpu.tcp import State, TcpConfig

    cfg = kw.pop("cfg", TcpConfig())
    cfg_server = kw.pop("cfg_server", cfg)
    client = TcpState(cfg, iss=1000)
    # server-side listener forks the actual connection on SYN
    listener = TcpState(cfg_server, iss=0)
    listener.listen()
    server_box: list[TcpState] = []

    client.connect(0)
    syn = client.poll_segments(0)[0]
    child = listener.accept_segment(latency_ns, syn, child_iss=5000)
    assert child is not None
    server_box.append(child)
    server = server_box[0]
    wire = Wire(client, server, latency_ns, **kw)
    wire.now = latency_ns
    wire.run(until=lambda: client.state == State.ESTABLISHED
             and server.state == State.ESTABLISHED)
    return client, server, wire


def transfer(src: TcpState, dst: TcpState, wire: Wire, data: bytes,
             max_steps: int = 50_000) -> bytes:
    """Send `data` src->dst until fully delivered; returns received bytes."""
    got = bytearray()
    sent = 0

    def pump() -> bool:
        nonlocal sent, got
        if sent < len(data):
            sent += src.send(data[sent : sent + 65536])
        while True:
            chunk = dst.recv(65536)
            if not chunk:
                break
            got += chunk
        return len(got) == len(data)

    wire.run(max_steps, until=pump)
    return bytes(got)
