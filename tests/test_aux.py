"""Auxiliary subsystems: DNS, checkpoint/resume, unblocked-syscall latency
model, parse/plot tools, shm-cleanup (SURVEY.md §5 + §2.1 dns.c/tracker)."""

from __future__ import annotations

import json
import subprocess
import sys

import numpy as np
import pytest

from shadow_tpu.config.options import ConfigOptions
from shadow_tpu.net.dns import Dns, DnsError

MS = 1_000_000
SEC = 1_000_000_000


# --------------------------------------------------------------------- dns


def test_dns_register_resolve_reverse():
    d = Dns()
    d.register("alpha", "10.0.0.1")
    d.register("beta", "10.0.0.2")
    assert d.resolve("alpha") == "10.0.0.1"
    assert d.resolve("10.0.0.9") == "10.0.0.9"  # literal passthrough
    assert d.resolve("gamma") is None
    assert d.reverse("10.0.0.2") == "beta"
    with pytest.raises(DnsError):
        d.register("alpha", "10.0.0.3")
    with pytest.raises(DnsError):
        d.register("other", "10.0.0.1")
    hosts = d.hosts_file()
    assert "10.0.0.1 alpha" in hosts and hosts.startswith("127.0.0.1 localhost")


# -------------------------------------------------------------- checkpoint
# Compiled-`Simulation` legs run in subprocesses (tests/subproc.py): this
# box's jaxlib heap corruption aborts in-process compiled runs — the
# assertion results come back as JSON, so nothing is gated any less.

_MODEL_CFG_SRC = '''
def _model_cfg(stop="4 s"):
    from shadow_tpu.config.options import ConfigOptions

    return ConfigOptions.from_dict(
        {
            "general": {"stop_time": stop, "seed": 17},
            "network": {"graph": {"type": "1_gbit_switch"}},
            "hosts": {
                "n": {
                    "count": 16,
                    "network_node_id": 0,
                    "processes": [
                        {
                            "model": "phold",
                            "model_args": {
                                "population": 2,
                                "mean_delay": "100 ms",
                                "size_bytes": 64,
                            },
                        }
                    ],
                }
            },
        }
    )
'''


def test_checkpoint_roundtrip_resumes_identically(tmp_path):
    from tests.subproc import run_isolated_json

    out = run_isolated_json(_MODEL_CFG_SRC + '''
import json, sys
from shadow_tpu.core.checkpoint import load_checkpoint, save_checkpoint
from shadow_tpu.sim import Simulation

# run A: straight to the end
a = Simulation(_model_cfg(), world=1)
a.run(progress=False)
digest_a = a.stats_report()["determinism_digest"]

# run B: stop half-way (engine chunks of 64 rounds), checkpoint, restore
# into a FRESH simulation, continue to the end
b = Simulation(_model_cfg(), world=1)
b.state = b.engine.run_chunk(b.state, b.params)  # partial progress
assert not bool(b.state.done)
ckpt = sys.argv[1]
save_checkpoint(ckpt, b)

c = Simulation(_model_cfg(), world=1)
load_checkpoint(ckpt, c)
assert int(c.state.now) == int(b.state.now)
c.run(progress=False)
print(json.dumps({"digest_a": digest_a,
                  "digest_c": c.stats_report()["determinism_digest"]}))
''', str(tmp_path / "sim.npz"))
    assert out["digest_c"] == out["digest_a"]


def test_checkpoint_rejects_mismatched_config(tmp_path):
    from tests.subproc import run_isolated_json

    out = run_isolated_json(_MODEL_CFG_SRC + '''
import json, sys
from shadow_tpu.core.checkpoint import (
    CheckpointError,
    load_checkpoint,
    save_checkpoint,
)
from shadow_tpu.sim import Simulation

a = Simulation(_model_cfg(), world=1)
ckpt = sys.argv[1]
save_checkpoint(ckpt, a)
other = _model_cfg(stop="9 s")  # different engine config
b = Simulation(other, world=1)
refused = False
try:
    load_checkpoint(ckpt, b)
except CheckpointError:
    refused = True
print(json.dumps({"refused": refused}))
''', str(tmp_path / "sim.npz"))
    assert out["refused"]


# ------------------------------------------- unblocked-syscall latency model


def test_busy_polling_program_advances_clock_when_modeled():
    from shadow_tpu.host import CpuHost, HostConfig

    def poller(ctx):
        # getpid in a tight loop never blocks; without the latency model the
        # simulated clock would freeze (reference handler/mod.rs:268-318)
        for _ in range(3000):
            yield ("getpid",)
        t = yield ("clock_gettime",)
        assert t > 0, "clock never advanced under busy polling"
        yield ("exit", 0)

    h = CpuHost(
        HostConfig(
            name="h",
            ip="10.0.0.1",
            model_unblocked_latency=True,
            unblocked_syscall_limit=1000,
            unblocked_syscall_latency_ns=1000,
        )
    )
    p = h.spawn(poller)
    h.execute(1 * SEC)
    assert p.exit_code == 0, p.stderr
    assert h.now() >= 2000  # at least two forced charges


def test_busy_polling_freezes_clock_when_not_modeled():
    from shadow_tpu.host import CpuHost, HostConfig

    seen = []

    def poller(ctx):
        for _ in range(3000):
            yield ("getpid",)
        seen.append((yield ("clock_gettime",)))
        yield ("exit", 0)

    h = CpuHost(HostConfig(name="h", ip="10.0.0.1"))
    h.spawn(poller)
    h.execute(1 * SEC)
    assert seen == [0]


# ------------------------------------------------------------------- tools


def test_parse_and_plot_tools(tmp_path):
    from shadow_tpu.cosim import HybridSimulation

    cfg = ConfigOptions.from_dict(
        {
            "general": {
                "stop_time": "1 s",
                "seed": 2,
                "data_directory": str(tmp_path / "data"),
            },
            "network": {"graph": {"type": "1_gbit_switch"}},
            "hosts": {
                "server": {
                    "network_node_id": 0,
                    "processes": [{"path": "udp_echo_server"}],
                },
                "client": {
                    "network_node_id": 0,
                    "processes": [
                        {
                            "path": "udp_ping",
                            "args": ["server=server", "count=2"],
                            "expected_final_state": {"exited": 0},
                        }
                    ],
                },
            },
        }
    )
    sim = HybridSimulation(cfg)
    sim.write_outputs(report=sim.run())
    log = tmp_path / "run.log"
    log.write_text(
        "[heartbeat] sim_time=0.500s wall=1.20s windows=10 ratio=0.42x\n"
        "[heartbeat] sim_time=1.000s wall=2.50s windows=20 ratio=0.40x\n"
    )
    parsed = tmp_path / "parsed.json"
    r = subprocess.run(
        [
            sys.executable,
            "tools/parse_shadow.py",
            str(tmp_path / "data"),
            "--log",
            str(log),
            "-o",
            str(parsed),
            # literal-line gate: a seed-generation heartbeat the parser
            # silently skipped would pass without --strict (shadowlint R5)
            "--strict",
        ],
        capture_output=True,
        text=True,
        timeout=60,
    )
    assert r.returncode == 0, r.stderr
    data = json.loads(parsed.read_text())
    assert data["sim_stats"]["process_failures"] == 0
    assert set(data["hosts"]) == {"server", "client"}
    assert len(data["heartbeats"]) == 2
    assert data["heartbeats"][0]["sim"] == 0.5

    plot = subprocess.run(
        [
            sys.executable,
            "tools/plot_shadow.py",
            str(parsed),
            "-o",
            str(tmp_path / "plot.png"),
        ],
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert plot.returncode in (0, 3)  # 3 = matplotlib unavailable
    if plot.returncode == 0:
        assert (tmp_path / "plot.png").exists()


def test_shm_cleanup_liveness(tmp_path):
    import os

    from shadow_tpu.native_plane import shm_cleanup

    dead = "/dev/shm/shadow-ipc-999999999-junk"  # pid can't exist
    alive = f"/dev/shm/shadow-ipc-{os.getpid()}-held"
    open(dead, "w").write("x")
    open(alive, "w").write("x")
    try:
        shm_cleanup()
        assert not os.path.exists(dead)  # orphan removed
        assert os.path.exists(alive)  # live owner's file kept
    finally:
        for p in (dead, alive):
            if os.path.exists(p):
                os.unlink(p)
    r = subprocess.run(
        [sys.executable, "-m", "shadow_tpu", "--shm-cleanup"],
        capture_output=True,
        text=True,
        timeout=60,
    )
    assert r.returncode == 0
    assert "removed" in r.stderr


def _hybrid_ckpt_cfg(stop="6 s"):
    """Mixed sim: modeled phold lanes stay active the whole horizon; one
    coroutine client finishes within the first second."""
    from shadow_tpu.config.options import ConfigOptions

    return ConfigOptions.from_dict(
        {
            "general": {"stop_time": stop, "seed": 5},
            "network": {"graph": {"type": "1_gbit_switch"}},
            "hosts": {
                "m": {
                    "count": 6,
                    "network_node_id": 0,
                    "processes": [{
                        "model": "phold",
                        "model_args": {"population": 2,
                                       "mean_delay": "150 ms"},
                    }],
                },
                "blaster": {
                    "network_node_id": 0,
                    "processes": [{
                        "path": "udp_blast",
                        "args": ["server=m1", "port=9000", "count=3"],
                        "expected_final_state": {"exited": 0},
                    }],
                },
            },
        }
    )


def test_hybrid_checkpoint_kill_and_resume(tmp_path):
    """VERDICT r3 missing #5: a MIXED simulation (device-modeled lanes +
    a real CPU-plane process phase) checkpoints after the process phase
    and resumes in a fresh build; the continuation is bit-identical to an
    uninterrupted run."""
    from shadow_tpu.core.checkpoint import (
        load_checkpoint_hybrid,
        save_checkpoint_hybrid,
    )
    from shadow_tpu.cosim import HybridSimulation

    a = HybridSimulation(_hybrid_ckpt_cfg("6 s"), world=1)
    ra = a.run(progress=False)
    assert ra["process_failures"] == 0

    b = HybridSimulation(_hybrid_ckpt_cfg("3 s"), world=1)
    rb = b.run(progress=False)
    assert rb["processes_exited"] == 1  # the client phase is over
    ckpt = save_checkpoint_hybrid(str(tmp_path / "hy.npz"), b)

    c = HybridSimulation(_hybrid_ckpt_cfg("6 s"), world=1)
    load_checkpoint_hybrid(ckpt, c)
    rc = c.run(progress=False)
    assert rc["determinism_digest"] == ra["determinism_digest"]
    assert rc["events_processed"] == ra["events_processed"]
    assert rc["packets_delivered"] == ra["packets_delivered"]
    assert rc["process_failures"] == 0


def test_byte_store_serialization_is_pickle_free():
    """ADVICE r4 medium: the payload byte store must round-trip without
    pickle (a tampered checkpoint file must never execute code on load).
    Covers both the plain-UDP and TCP-segment packet shapes."""
    from shadow_tpu.core.checkpoint import (
        _pack_byte_stores,
        _unpack_byte_stores,
    )
    from shadow_tpu.host.sockets import NetPacket
    from shadow_tpu.tcp.segment import ACK, PSH, Segment

    seg = Segment(flags=ACK | PSH, seq=1000, ack=77, wnd=65535,
                  payload=b"tcp-bytes", mss=1460, wscale=7,
                  src_port=4000, dst_port=80)
    stores = [
        {3: (0, NetPacket("11.0.0.1", 9000, "11.0.0.2", 9001, 17,
                          b"udp-payload"))},
        {},
        {9: (2, NetPacket("11.0.0.2", 4000, "11.0.0.1", 80, 6,
                          b"tcp-bytes", seg=seg))},
    ]
    idx, buf = _pack_byte_stores(stores)
    assert b"pickle" not in idx  # plain JSON index
    out = _unpack_byte_stores(idx, buf, 3)
    assert out[1] == {}
    w, pkt = out[0][3]
    assert (w, pkt.payload, pkt.dst_ip) == (0, b"udp-payload", "11.0.0.2")
    w, pkt = out[2][9]
    assert w == 2 and pkt.seg == seg and pkt.payload == b"tcp-bytes"


def test_hybrid_checkpoint_refuses_live_processes(tmp_path):
    """A hybrid sim with a still-running process refuses to snapshot
    (live coroutine/OS state cannot be serialized) — loud, not silent."""
    import pytest as _pytest

    from shadow_tpu.core.checkpoint import (
        CheckpointError,
        save_checkpoint_hybrid,
    )
    from shadow_tpu.cosim import HybridSimulation

    # freshly built, never run: the client process has not exited yet
    sim = HybridSimulation(_hybrid_ckpt_cfg("2 s"), world=1)
    with _pytest.raises(CheckpointError):
        save_checkpoint_hybrid(str(tmp_path / "no.npz"), sim)
    for h in sim.hosts:
        h.shutdown()
