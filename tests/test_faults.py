"""Fault plane (PR 5): deterministic in-sim fault injection + the
crash-resilient run supervisor.

The contract has three legs (docs/architecture.md "Fault plane"):

  1. faults ABSENT  => the engine program is bit-identical to the
     fault-free build — digests, event counts, every drop counter —
     across echo/phold/tgen x flat/bucketed queues x K in {1, 4} x
     world in {1, 8} (the test_gears gate pattern, extended);
  2. faults PRESENT => same fault seed, same digest: across reruns,
     across mesh shapes / queue layouts / K-folds, and across a mid-run
     snapshot + restore (recovery exactness);
  3. the supervisor survives injected dispatch failures with bounded
     retries (digest-identical to an uninterrupted run), and a forced
     permanent failure still exports sim-stats/trace artifacts for the
     completed prefix. The end-to-end SIGKILL + on-disk-checkpoint resume
     runs in a subprocess (tests/subproc.py) like every compiled
     Simulation leg on this box.
"""

from __future__ import annotations

import os
import sys

import jax
import numpy as np
import pytest

from shadow_tpu.config.options import ConfigError, FaultOptions
from shadow_tpu.core import Engine
from shadow_tpu.core.faults import (
    compile_faults,
    fault_u64,
    fault_uniform,
)
from shadow_tpu.core.supervisor import (
    ChunkSupervisor,
    SupervisorAbort,
    state_digest_sig,
)
from tests.engine_harness import build_sim, mk_hosts

# the test_gears workload trio: short horizons, exchange-heavy enough to
# exercise the merge (and under faults, the crash/loss paths) every round
_CASES = {
    "phold": ("phold", mk_hosts(8, {"mean_delay": "20 ms", "population": 3}),
              300_000_000, dict(loss=0.1)),
    "echo": ("udp_echo",
             [dict(host_id=0, name="server", start_time=0,
                   model_args={"role": "server"})]
             + [dict(host_id=i, name=f"c{i}", start_time=0,
                     model_args={"role": "client", "peer": "server",
                                 "interval": "4 ms", "size_bytes": 2000})
                for i in range(1, 5)],
             200_000_000, dict(bw_bits=2_000_000, loss=0.05)),
    "tgen": ("tgen_tcp",
             mk_hosts(5, {"flow_segs": 8, "flows": 1, "cwnd_cap": 8,
                          "rto_min": "100 ms"}),
             1_500_000_000,
             dict(loss=0.05, latency=10_000_000, sends_budget=16)),
}

# a schedule whose windows land inside every case's horizon: churn hits
# ~half the hosts with ~50 ms outages, the link fault covers [50, 150) ms
_FAULTS = {
    "seed": 7,
    "restart_queue": "hold",
    "host_churn": {"prob": 0.5, "mean_downtime": "0.05 s"},
    "loss_windows": [{"start": "0.05 s", "end": "0.15 s", "loss": 0.3,
                      "latency_factor": 2.0}],
}


def _build(model, hosts, stop, world=1, **kw):
    cfg, m, params, mstate, events = build_sim(
        model, hosts, stop, world=world, **kw
    )
    mesh = None
    if world > 1:
        mesh = jax.sharding.Mesh(
            np.array(jax.devices()[:world]), ("hosts",)
        )
    eng = Engine(cfg, m, mesh)
    state, params = eng.init_state(params, mstate, events, seed=1)
    return cfg, eng, state, params


def _run(model, hosts, stop, world=1, **kw):
    _, eng, state, params = _build(model, hosts, stop, world, **kw)
    chunks = 0
    while not bool(state.done):
        state = eng.run_chunk(state, params)
        chunks += 1
        assert chunks < 500, "simulation failed to terminate"
    return state


def _assert_identical(a, b):
    fa = jax.device_get(a.stats)
    fb = jax.device_get(b.stats)
    np.testing.assert_array_equal(np.asarray(fa.digest), np.asarray(fb.digest))
    np.testing.assert_array_equal(np.asarray(fa.events), np.asarray(fb.events))
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(a.queue.dropped)),
        np.asarray(jax.device_get(b.queue.dropped)),
    )
    for field in ("pkts_sent", "pkts_lost", "pkts_codel_dropped",
                  "pkts_budget_dropped", "pkts_delivered",
                  "faults_dropped", "faults_delayed"):
        np.testing.assert_array_equal(
            np.asarray(getattr(fa, field)), np.asarray(getattr(fb, field)),
            err_msg=field,
        )


# ------------------------------------------------- 1: faults-absent gate

_BASELINES: dict = {}


def _baseline(case):
    if case not in _BASELINES:
        model, hosts, stop, kw = _CASES[case]
        _BASELINES[case] = _run(model, hosts, stop, **kw)
    return _BASELINES[case]


@pytest.mark.parametrize("qb", [0, 8], ids=["flat", "bucketed"])
@pytest.mark.parametrize("k", [1, 4], ids=["k1", "k4"])
@pytest.mark.parametrize("case", sorted(_CASES), ids=sorted(_CASES))
def test_faults_absent_bit_identical(case, k, qb):
    """The acceptance gate: with no `faults:` block the fault-plane
    plumbing traces in NOTHING — digests, events, and every drop counter
    stay bit-identical across queue layouts and K-folds (any perturbation
    of the baseline program by this PR's engine edits shows up here)."""
    if k == 1 and qb == 0:
        _baseline(case)  # the reference leg itself
        return
    model, hosts, stop, kw = _CASES[case]
    got = _run(model, hosts, stop, queue_block=qb, microstep_events=k, **kw)
    _assert_identical(_baseline(case), got)


# mesh legs need a host count divisible by world=8: the echo/tgen cases
# grow to 8 hosts, so each compares world 1 vs 8 on ITS OWN host set
_MESH_CASES = {
    "phold": _CASES["phold"],
    "echo": ("udp_echo",
             [dict(host_id=0, name="server", start_time=0,
                   model_args={"role": "server"})]
             + [dict(host_id=i, name=f"c{i}", start_time=0,
                     model_args={"role": "client", "peer": "server",
                                 "interval": "4 ms", "size_bytes": 2000})
                for i in range(1, 8)],
             200_000_000, dict(bw_bits=2_000_000, loss=0.05)),
    "tgen": ("tgen_tcp",
             mk_hosts(8, {"flow_segs": 8, "flows": 1, "cwnd_cap": 8,
                          "rto_min": "100 ms"}),
             1_500_000_000,
             dict(loss=0.05, latency=10_000_000, sends_budget=16)),
}


@pytest.mark.parametrize("case", sorted(_MESH_CASES), ids=sorted(_MESH_CASES))
def test_faults_absent_mesh_invariant(case):
    """world=8 leg of the gate (the conftest's virtual devices)."""
    model, hosts, stop, kw = _MESH_CASES[case]
    one = _run(model, hosts, stop, world=1, **kw)
    got = _run(model, hosts, stop, world=8, **kw)
    _assert_identical(one, got)


# -------------------------------------------- 2: seeded-fault determinism


@pytest.mark.parametrize("case", sorted(_CASES), ids=sorted(_CASES))
def test_fault_seed_deterministic_and_firing(case):
    """Same fault seed => same digest across reruns; and the schedule
    genuinely fires (drop/delay counters nonzero) so the determinism
    claim is about a REAL fault run, not an inert one."""
    model, hosts, stop, kw = _CASES[case]
    a = _run(model, hosts, stop, faults=_FAULTS, **kw)
    b = _run(model, hosts, stop, faults=_FAULTS, **kw)
    _assert_identical(a, b)
    sa = jax.device_get(a.stats)
    assert (int(np.asarray(sa.faults_dropped).sum())
            + int(np.asarray(sa.faults_delayed).sum())) > 0


def test_fault_mesh_queue_k_invariant():
    """Faulty runs stay bit-identical across mesh shapes, queue layouts,
    and K-folds — the per-host masked-advance RNG and the head-time crash
    gating are both shard- and batch-shape independent."""
    model, hosts, stop, kw = _CASES["phold"]
    base = _run(model, hosts, stop, faults=_FAULTS, **kw)
    for variant in (
        dict(world=8),
        dict(world=8, exchange="alltoall"),
        dict(queue_block=8, qcap=32),
        dict(microstep_events=4),
        dict(microstep_events=4, queue_block=8, qcap=32),
    ):
        got = _run(model, hosts, stop, faults=_FAULTS, **{**kw, **variant})
        _assert_identical(base, got)


def test_fault_clear_cpu_k_invariant():
    """clear + cpu_delay corner: the down check must read the EXECUTION
    time (the CPU-busy floor can push an event across a crash-window
    boundary), identically at K=1 and inside the K-way fold."""
    model, hosts, stop, kw = _CASES["phold"]
    f = {"seed": 7, "restart_queue": "clear",
         "host_churn": {"prob": 0.6, "mean_downtime": "0.04 s"}}
    kw = dict(kw, cpu_delay_ns=3_000_000)  # busy floor rewrites exec times
    a = _run(model, hosts, stop, faults=f, **kw)
    b = _run(model, hosts, stop, faults=f, microstep_events=4, **kw)
    _assert_identical(a, b)
    assert int(
        np.asarray(jax.device_get(a.stats).faults_dropped).sum()
    ) > 0


_SNAPSHOT_RESUME_SCRIPT = """
import json, sys
import jax
import numpy as np
from shadow_tpu.core import Engine
from shadow_tpu.core.checkpoint import restore_snapshot, snapshot_state
from tests.engine_harness import build_sim, mk_hosts

faults = json.loads(sys.argv[1])
hosts = mk_hosts(8, {"mean_delay": "20 ms", "population": 3})
cfg, m, params, mstate, events = build_sim(
    "phold", hosts, 300_000_000, faults=faults, loss=0.1,
    rounds_per_chunk=2,  # so the snapshot lands genuinely mid-run
)
eng = Engine(cfg, m, None)
state, params = eng.init_state(params, mstate, events, seed=1)
state = eng.run_chunk(state, params)
state = eng.run_chunk(state, params)
assert not bool(state.done)
snap = snapshot_state(state)


def summary(state):
    s = jax.device_get(state.stats)
    return {"digest": np.asarray(s.digest).reshape(-1).tolist(),
            "events": int(np.asarray(s.events).sum()),
            "dropped": int(np.asarray(s.faults_dropped).sum()),
            "delayed": int(np.asarray(s.faults_delayed).sum())}


a = state
while not bool(a.done):
    a = eng.run_chunk(a, params)
b = restore_snapshot(snap)
while not bool(b.done):
    b = eng.run_chunk(b, params)
print(json.dumps({"clean": summary(a), "sup": summary(b)}))
"""


def _classified_digest_compare(attempt, what: str):
    """Run `attempt() -> {"clean": ..., "sup": ...}` up to 3 times; pass
    as soon as the two summaries match. On 3 mismatches, classify the way
    tools/soak.py does: the SAME mismatch reproducing across fresh
    subprocesses is a deterministic bug (fail); VARYING mismatches are
    this box's documented device-memory scribble (CHANGES.md PR 2 env
    note) — skip, never silently pass."""
    outs = []
    for _ in range(3):
        out = attempt()
        if out["sup"] == out["clean"]:
            return out
        outs.append(out)
    pairs = {
        (tuple(o["clean"]["digest"]), tuple(o["sup"]["digest"]))
        for o in outs
    }
    assert len(pairs) > 1, (
        f"{what} deterministically diverges (identical mismatch on 3 "
        f"fresh attempts): {outs[0]}"
    )
    pytest.skip(
        f"{what} digests mismatched DIFFERENTLY across 3 attempts: this "
        "box's documented device-memory scribble (CHANGES.md PR 2 env "
        "note), not a deterministic bug"
    )


def test_fault_snapshot_resume_exact():
    """Recovery exactness at the engine level: snapshot mid-run, finish;
    restore the snapshot, finish again — bit-identical (the property the
    supervisor's replay and the on-disk resume both stand on). Runs in
    the subprocess harness: the rounds_per_chunk=2 dispatch pattern is a
    magnet for this box's corruption (measured segfaulting mid-pytest on
    pre-PR HEAD too), and completed-run mismatches get the scribble
    classification."""
    import json as _json

    from tests.subproc import run_isolated_json

    _classified_digest_compare(
        lambda: run_isolated_json(
            _SNAPSHOT_RESUME_SCRIPT, _json.dumps(_FAULTS)
        ),
        "snapshot-resume replay",
    )


def test_hold_vs_clear_semantics():
    """queue-hold defers a down host's events (counted delayed, none
    dropped by the crash plane); queue-clear consumes-and-drops them.
    Both are real behavioral differences, so their digests differ from
    each other and from the fault-free run."""
    model, hosts, stop, kw = _CASES["phold"]
    crash_only = {"seed": 7, "host_churn": {"prob": 0.5,
                                            "mean_downtime": "0.05 s"}}
    hold = _run(model, hosts, stop,
                faults=dict(crash_only, restart_queue="hold"), **kw)
    clear = _run(model, hosts, stop,
                 faults=dict(crash_only, restart_queue="clear"), **kw)
    sh = jax.device_get(hold.stats)
    sc = jax.device_get(clear.stats)
    assert int(np.asarray(sh.faults_dropped).sum()) == 0
    assert int(np.asarray(sh.faults_delayed).sum()) > 0
    assert int(np.asarray(sc.faults_dropped).sum()) > 0
    # clear loses events hold preserves
    assert (int(np.asarray(sc.events).sum())
            < int(np.asarray(sh.events).sum()))
    assert not np.array_equal(np.asarray(sh.digest), np.asarray(sc.digest))


def test_loss_window_honors_bootstrap():
    """Fault loss AND latency inflation obey general.bootstrap_end_time
    exactly like path loss: a window entirely inside the bootstrap phase
    drops nothing and delays nothing."""
    model, hosts, stop, kw = _CASES["echo"]
    lossy = {"seed": 7, "loss_windows": [
        {"start": "0.01 s", "end": "0.06 s", "loss": 1.0,
         "latency_factor": 3.0}]}
    hot = _run(model, hosts, stop, faults=lossy, **kw)
    gated = _run(model, hosts, stop, faults=lossy,
                 bootstrap_end=100_000_000, **kw)
    assert int(np.asarray(jax.device_get(hot.stats).faults_dropped).sum()) > 0
    gs = jax.device_get(gated.stats)
    assert int(np.asarray(gs.faults_dropped).sum()) == 0
    assert int(np.asarray(gs.faults_delayed).sum()) == 0


def test_latency_inflation_delays_without_dropping():
    """A pure latency-inflation window delays deliveries (counted) and
    drops nothing; moving arrivals is a real behavioral change, so the
    digest differs from the fault-free run."""
    model, hosts, stop, kw = _CASES["echo"]
    slow = {"seed": 7, "loss_windows": [
        {"start": "0.05 s", "end": "0.15 s", "latency_factor": 3.0}]}
    got = _run(model, hosts, stop, faults=slow, **kw)
    s = jax.device_get(got.stats)
    assert int(np.asarray(s.faults_dropped).sum()) == 0
    assert int(np.asarray(s.faults_delayed).sum()) > 0
    base = _baseline("echo")
    assert not np.array_equal(
        np.asarray(jax.device_get(base.stats).digest), np.asarray(s.digest)
    )


def test_fault_trace_columns():
    """The trace ring's fault columns reconcile with the device counters
    and the hosts_down gauge sees the churn windows."""
    from shadow_tpu.obs.tracer import RoundTracer

    model, hosts, stop, kw = _CASES["phold"]
    _, eng, state, params = _build(
        model, hosts, stop, faults=_FAULTS, trace_rounds=64, **kw
    )
    tracer = RoundTracer(64)
    tracer.sync_cursor(state.trace)
    while not bool(state.done):
        state = eng.run_chunk(state, params)
        jax.block_until_ready(state)
        tracer.drain(state.trace)
    t = tracer.totals()
    s = jax.device_get(state.stats)
    assert t["faults_dropped"] == int(np.asarray(s.faults_dropped).sum())
    assert t["faults_delayed"] == int(np.asarray(s.faults_delayed).sum())
    assert t["hosts_down_max"] > 0


# ------------------------------------------------------- 3: supervisor


_SUPERVISOR_RETRY_SCRIPT = """
import json, sys
import jax
import numpy as np
from shadow_tpu.core import Engine
from shadow_tpu.core.supervisor import ChunkSupervisor
from tests.engine_harness import build_sim, mk_hosts

faults = json.loads(sys.argv[1])
hosts = mk_hosts(8, {"mean_delay": "20 ms", "population": 3})
# several chunks (so the injected failures land mid-run) via a LONGER
# horizon, not tiny chunks: rounds_per_chunk=2 multiplies the dispatch
# count ~4x and with it this box's corruption rate
kw = dict(loss=0.1, rounds_per_chunk=8)


def build():
    cfg, m, params, mstate, events = build_sim(
        "phold", hosts, 1_500_000_000, faults=faults, **kw
    )
    eng = Engine(cfg, m, None)
    state, params = eng.init_state(params, mstate, events, seed=1)
    return eng, state, params


def summary(state):
    s = jax.device_get(state.stats)
    return {"digest": np.asarray(s.digest).reshape(-1).tolist(),
            "events": int(np.asarray(s.events).sum()),
            "dropped": int(np.asarray(s.faults_dropped).sum()),
            "delayed": int(np.asarray(s.faults_delayed).sum())}


eng, state, params = build()
while not bool(state.done):
    state = eng.run_chunk(state, params)
clean = summary(state)

eng, state, params = build()
sup = ChunkSupervisor(snapshot_every_chunks=1, max_retries=3,
                      backoff_base_s=0.001)
sup.note_state(state)
calls = {"n": 0}


def flaky(st):
    calls["n"] += 1
    if calls["n"] in (2, 4):
        raise RuntimeError("injected dispatch failure")
    return eng.run_chunk(st, params)


chunks = 0
while not bool(state.done):
    state = sup.run_chunk(state, flaky)
    chunks += 1
    assert chunks < 500
print(json.dumps({"clean": clean, "sup": summary(state),
                  "retries": sup.retries, "restores": sup.restores,
                  "aborted": sup.aborted}))
"""


def test_supervisor_retries_transient_failures_exactly():
    """Injected dispatch failures (raise on chunks 2 and 4) recover from
    the periodic snapshot with bounded retries, and the final digest is
    bit-identical to an uninterrupted run.

    Env note: this leg is THE magnet for the box's known jaxlib
    corruption (two full engine builds + replay traffic in one process —
    measured in-process SIGABRT/SIGSEGV on MOST runs, killing the whole
    pytest process, and re-verified on pre-PR HEAD with no fault plane
    at all), so it runs in the subprocess harness like the
    compiled-Simulation legs and skips (never silently passes) on the
    crash signature. The corruption can also scribble device state into
    a wrong digest instead of aborting (CHANGES.md PR 2), so completed
    attempts are CLASSIFIED the way tools/soak.py classifies: the
    supervisor mechanics (retries/restores/aborted — host-side Python,
    scribble-proof) assert hard on every attempt; a digest mismatch that
    REPRODUCES IDENTICALLY across 3 fresh subprocesses is a
    deterministic replay bug and fails; mismatching digests that VARY
    across attempts are the documented scribble and skip."""
    import json as _json

    from tests.subproc import run_isolated_json

    def attempt():
        out = run_isolated_json(
            _SUPERVISOR_RETRY_SCRIPT, _json.dumps(_FAULTS)
        )
        assert out["retries"] == 2 and out["restores"] == 2
        assert not out["aborted"]
        return out

    _classified_digest_compare(attempt, "supervised replay")


def test_supervisor_bounded_abort_keeps_last_good_state():
    """A permanent failure aborts after max_retries, and last_good()
    hands back the pre-failure snapshot (the completed prefix)."""
    model, hosts, stop, kw = _CASES["phold"]
    kw = dict(kw, rounds_per_chunk=2)
    _, eng, state, params = _build(model, hosts, stop, faults=_FAULTS, **kw)
    sup = ChunkSupervisor(snapshot_every_chunks=1, max_retries=2,
                          backoff_base_s=0.001)
    sup.note_state(state)
    state = sup.run_chunk(state, lambda st: eng.run_chunk(st, params))
    good_sig = state_digest_sig(state)

    def broken(st):
        raise RuntimeError("permanent dispatch failure")

    with pytest.raises(SupervisorAbort):
        sup.run_chunk(state, broken)
    assert sup.aborted and sup.retries == 3  # max_retries + the first try
    assert state_digest_sig(sup.last_good()) == good_sig
    assert sup.poisoned_state() is None  # only the poisoned path uses it


def test_supervisor_restore_resets_snapshot_cadence():
    """A restore rewinds progress to the snapshot point, so the snapshot
    cadence restarts from zero — a recovery must not trip an early
    snapshot (extra HBM copy + on-disk write) on the first replayed
    chunk."""
    model, hosts, stop, kw = _CASES["phold"]
    kw = dict(kw, rounds_per_chunk=2)
    _, eng, state, params = _build(model, hosts, stop, faults=_FAULTS, **kw)
    sup = ChunkSupervisor(snapshot_every_chunks=3, max_retries=2,
                          backoff_base_s=0.001)
    sup.note_state(state)
    ok = lambda st: eng.run_chunk(st, params)
    state = sup.run_chunk(state, ok)  # 1 chunk since snapshot

    fails = iter([True])

    def flaky(st):
        if next(fails, False):
            raise RuntimeError("transient dispatch failure")
        return eng.run_chunk(st, params)

    # fail -> restore (cadence resets) -> replay ok: 1 chunk since restore
    state = sup.run_chunk(state, flaky)
    assert sup.restores == 1 and sup.snapshots == 1
    state = sup.run_chunk(state, ok)  # 2 since restore: still no snapshot
    assert sup.snapshots == 1
    state = sup.run_chunk(state, ok)  # 3 since restore: cadence fires
    assert sup.snapshots == 2


def test_supervisor_digest_cross_check_detects_divergence():
    """A snapshot whose restored digest no longer matches the recorded
    signature must abort (silent-divergence corruption), not replay."""
    model, hosts, stop, kw = _CASES["phold"]
    _, eng, state, params = _build(model, hosts, stop, faults=_FAULTS, **kw)
    sup = ChunkSupervisor(snapshot_every_chunks=1, max_retries=2,
                          backoff_base_s=0.001)
    sup.note_state(state)
    sup._snap_sig = (sup._snap_sig[0], sup._snap_sig[1] ^ 0xDEAD)  # poison

    def fail_once(st):
        raise RuntimeError("trigger a restore")

    with pytest.raises(SupervisorAbort, match="cross-check"):
        sup.run_chunk(state, fail_once)
    # the snapshot is now untrustworthy: the supervisor must refuse to
    # hand it back as a GOOD prefix, and must say so in the report —
    # but the export fallback still materializes (the driver's in-hand
    # state may hold donated-away buffers; artifacts flag poisoned=true)
    assert sup.poisoned
    assert sup.last_good() is None
    assert sup.poisoned_state() is not None
    assert sup.report()["poisoned"] is True


# -------------------------------------------- compile / config units


def test_compile_faults_units():
    fo = FaultOptions.from_dict({
        "seed": 3,
        "crashes": [
            {"host": 1, "down_at": "1 s", "up_at": "2 s"},
            {"host": 1, "down_at": "1.5 s", "up_at": "3 s"},  # overlaps
            {"host": "h2", "down_at": "4 s", "up_at": "5 s"},
        ],
    })
    sched = compile_faults(
        fo, num_hosts=4, stop_time=10_000_000_000,
        name_to_id={"h2": 2},
    )
    assert sched.active and sched.crash_windows == 1  # merged to one window
    down = np.asarray(sched.params.down_t)
    up = np.asarray(sched.params.up_t)
    assert down[1, 0] == 1_000_000_000 and up[1, 0] == 3_000_000_000
    assert down[2, 0] == 4_000_000_000
    assert sched.loss_windows == 0 and sched.params.win_start is None

    # churn is a pure function of the fault seed
    fo2 = FaultOptions.from_dict(
        {"seed": 9, "host_churn": {"prob": 0.5, "mean_downtime": "1 s"}}
    )
    s1 = compile_faults(fo2, num_hosts=16, stop_time=10_000_000_000)
    s2 = compile_faults(fo2, num_hosts=16, stop_time=10_000_000_000)
    np.testing.assert_array_equal(
        np.asarray(s1.params.down_t), np.asarray(s2.params.down_t)
    )
    # mesh padding never churns: padded lanes carry no windows
    s3 = compile_faults(fo2, num_hosts=24, num_real=16,
                        stop_time=10_000_000_000)
    assert (np.asarray(s3.params.down_t)[16:] == np.iinfo(np.int64).max).all()
    np.testing.assert_array_equal(
        np.asarray(s3.params.down_t)[:16], np.asarray(s1.params.down_t)
    )

    with pytest.raises(ValueError, match="unknown host"):
        compile_faults(
            FaultOptions.from_dict(
                {"crashes": [{"host": "nope", "down_at": "1 s",
                              "up_at": "2 s"}]}
            ),
            num_hosts=4, stop_time=10_000_000_000, name_to_id={},
        )
    with pytest.raises(ValueError, match="out of range"):
        compile_faults(
            FaultOptions.from_dict(
                {"crashes": [{"host": 9, "down_at": "1 s", "up_at": "2 s"}]}
            ),
            num_hosts=4, stop_time=10_000_000_000,
        )
    # the CLI-override path can setattr restart_queue raw — the compiler
    # must reject unknown policies rather than silently degrade to hold
    bad = FaultOptions.from_dict({"host_churn": {"prob": 0.5}})
    bad.restart_queue = "wipe"
    with pytest.raises(ValueError, match="hold\\|clear"):
        compile_faults(bad, num_hosts=4, stop_time=10_000_000_000)


def test_fault_rng_counter_based():
    """Schedule draws are pure functions of (seed, host, counter)."""
    a = fault_u64(1, np.arange(8), 0)
    b = fault_u64(1, np.arange(8), 0)
    np.testing.assert_array_equal(a, b)
    assert (fault_u64(1, np.arange(8), 1) != a).any()
    assert (fault_u64(2, np.arange(8), 0) != a).any()
    u = fault_uniform(1, np.arange(1000), 0)
    assert (0 <= u).all() and (u < 1).all()


def test_fault_options_parse():
    f = FaultOptions.from_dict(None)
    assert not f.injecting and not f.supervisor.enabled
    f = FaultOptions.from_dict({
        "seed": 5,
        "restart_queue": "clear",
        "host_churn": {"prob": 0.2, "mean_downtime": "2 s"},
        "loss_windows": [{"start": "1 s", "end": "2 s", "loss": 0.5,
                          "latency_factor": 1.5}],
        "supervisor": {"snapshot_every_chunks": 4,
                       "checkpoint_file": "ck.npz", "max_retries": 5},
    })
    assert f.injecting and f.supervisor.enabled
    assert f.host_churn.mean_downtime == 2_000_000_000
    assert f.loss_windows[0].start == 1_000_000_000
    with pytest.raises(ConfigError, match="restart_queue"):
        FaultOptions.from_dict({"restart_queue": "wipe"})
    with pytest.raises(ConfigError, match="prob"):
        FaultOptions.from_dict({"host_churn": {"prob": 1.5}})
    with pytest.raises(ConfigError, match="latency_factor"):
        FaultOptions.from_dict({"loss_windows": [
            {"start": "1 s", "end": "2 s", "latency_factor": 0.5}]})
    with pytest.raises(ConfigError, match="loss"):
        FaultOptions.from_dict({"loss_windows": [
            {"start": "1 s", "end": "2 s", "loss": 2.0}]})
    with pytest.raises(ConfigError, match="unknown faults"):
        FaultOptions.from_dict({"nope": 1})
    with pytest.raises(ConfigError, match="snapshot_every_chunks"):
        FaultOptions.from_dict({"supervisor": {"snapshot_every_chunks": -1}})


def test_engine_rejects_mismatched_fault_wiring():
    """EngineConfig fault dims and EngineParams.faults must agree."""
    from shadow_tpu.core import EngineConfig

    with pytest.raises(ValueError, match="fault window"):
        EngineConfig(num_hosts=4, stop_time=1, fault_crash_windows=-1)
    # config says faults, params carry none -> loud at init_state
    cfg, model, params, mstate, events = build_sim(
        "phold", mk_hosts(4, {"mean_delay": "20 ms", "population": 2}),
        100_000_000,
    )
    import dataclasses

    bad = dataclasses.replace(cfg, fault_crash_windows=1)
    eng = Engine(bad, model, None)
    with pytest.raises(ValueError, match="FaultSchedule"):
        eng.init_state(params, mstate, events, seed=1)


def test_hybrid_rejects_crashes_allows_loss_windows():
    """The hybrid driver refuses crash schedules (live CPU processes
    cannot pause) but accepts link-fault windows."""
    from shadow_tpu.config.options import ConfigOptions
    from shadow_tpu.cosim import HybridSimulation

    base = {
        "general": {"stop_time": "1 s", "seed": 1},
        "network": {"graph": {"type": "1_gbit_switch"}},
        "hosts": {
            "a": {"network_node_id": 0,
                  "processes": [{"path": "udp_echo_server"}]},
        },
    }
    cfg = ConfigOptions.from_dict({
        **base,
        "faults": {"host_churn": {"prob": 0.5}},
    })
    with pytest.raises(ConfigError, match="hybrid"):
        HybridSimulation(cfg, world=1)
    # a durability knob the hybrid cannot honor is equally loud: its
    # per-dispatch supervisor never writes on-disk checkpoints (the CPU
    # plane cannot resume from a device checkpoint), so accepting
    # checkpoint_file would be a silent drop discovered at crash time
    cfg = ConfigOptions.from_dict({
        **base,
        "faults": {"supervisor": {"snapshot_every_chunks": 1,
                                  "checkpoint_file": "ck.npz"}},
    })
    with pytest.raises(ConfigError, match="checkpoint_file"):
        HybridSimulation(cfg, world=1)


def test_golden_scheduler_rejects_faults():
    from shadow_tpu.config.options import ConfigOptions
    from shadow_tpu.sim import Simulation

    cfg = ConfigOptions.from_dict({
        "general": {"stop_time": "1 s", "seed": 1},
        "network": {"graph": {"type": "1_gbit_switch"}},
        "experimental": {"scheduler": "cpu-reference"},
        "faults": {"host_churn": {"prob": 0.5}},
        "hosts": {"n": {"count": 4, "network_node_id": 0,
                        "processes": [{"model": "timer",
                                       "model_args": {"interval": "100 ms"}}]}},
    })
    with pytest.raises(ConfigError, match="cpu-reference"):
        Simulation(cfg, world=1)


# -------------------------------------- heartbeat / subproc satellites


def test_heartbeat_regex_faults_and_old_formats():
    """parse_shadow must read the new faults= field AND keep parsing the
    older line formats verbatim (one literal line per generation)."""
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from tools.parse_shadow import HEARTBEAT_RE

    faulty = ("[heartbeat] sim_time=1.043s wall=1.83s events=400 rounds=264 "
              "msteps/round=1.0 ev/mstep=1.44 ici_bytes=0 q_hwm=8 "
              "faults=20/38 ratio=0.57x rss_gib=0.85")
    m = HEARTBEAT_RE.search(faulty)
    assert m and m.group("faults_dropped") == "20"
    assert m.group("faults_delayed") == "38" and m.group("ratio") == "0.57"
    # literal PRE-fault-plane formats, one per generation:
    old_pr2 = ("[heartbeat] sim_time=1.000s wall=2.50s events=100 rounds=10 "
               "msteps/round=3.0 ev/mstep=3.33 ratio=0.40x rss_gib=1.00")
    m = HEARTBEAT_RE.search(old_pr2)
    assert m and m.group("faults_dropped") is None
    assert m.group("ratio") == "0.40"
    old_pr4 = ("[heartbeat] sim_time=1.000s wall=2.50s events=100 rounds=10 "
               "msteps/round=3.0 ev/mstep=3.33 ici_bytes=4096 q_hwm=7 "
               "gear=2 ratio=0.40x rss_gib=1.00")
    m = HEARTBEAT_RE.search(old_pr4)
    assert m and m.group("gear") == "2" and m.group("faults_dropped") is None
    hybrid = ("[heartbeat] sim_time=1.000s wall=2.50s windows=10 "
              "faults=3/4 gear=4 ratio=0.40x")
    m = HEARTBEAT_RE.search(hybrid)
    assert m and m.group("faults_dropped") == "3" and m.group("windows") == "10"


def test_subproc_retries_one_off_abort(tmp_path):
    """tests/subproc.py retries the corruption signature once: a script
    that aborts on its first attempt and succeeds on the second must
    come back as a normal completed process, not a skip."""
    from tests.subproc import run_isolated

    sentinel = tmp_path / "second_try"
    script = f"""
import os, sys
p = {str(sentinel)!r}
if not os.path.exists(p):
    open(p, "w").close()
    os.abort()
print("survived")
"""
    proc = run_isolated(script, prelude=False)
    assert proc.returncode == 0 and "survived" in proc.stdout


def test_subproc_skips_after_exhausted_retries():
    from _pytest.outcomes import Skipped

    from tests.subproc import run_isolated

    with pytest.raises(Skipped, match="2/2 attempts"):
        run_isolated("import os; os.abort()", prelude=False)


# -------------------------------------- compiled-Simulation legs (subproc)

_KILL_RESUME_SCRIPT = """
import json, os, sys
from shadow_tpu.config.options import ConfigOptions
from shadow_tpu.sim import Simulation
from shadow_tpu.core.checkpoint import load_checkpoint

data_dir, mode = sys.argv[1], sys.argv[2]
cfgd = {
  'general': {'stop_time': '2 s', 'seed': 1, 'heartbeat_interval': None,
              'data_directory': data_dir},
  'network': {'graph': {'type': '1_gbit_switch'}},
  'experimental': {'event_queue_capacity': 16, 'rounds_per_chunk': 8},
  'faults': {'seed': 7,
             'host_churn': {'prob': 0.4, 'mean_downtime': '0.3 s'},
             'supervisor': {'snapshot_every_chunks': 2,
                            'checkpoint_file': 'resume.npz'}},
  'hosts': {'node': {'count': 12, 'network_node_id': 0,
      'processes': [{'model': 'phold',
                     'model_args': {'population': 2, 'mean_delay': '100 ms',
                                    'size_bytes': 64}}]}},
}
cfg = ConfigOptions.from_dict(cfgd)
sim = Simulation(cfg, world=1)
ck = os.path.join(data_dir, 'resume.npz')
if mode == 'resume' and os.path.exists(ck):
    load_checkpoint(ck, sim)
rep = sim.run(log=sys.stderr)
print(json.dumps({'digest': rep['determinism_digest'],
                  'events': rep['events_processed'],
                  'supervisor': rep.get('supervisor')}))
"""


def test_kill_resume_digest_equal(tmp_path):
    """The satellite crash-recovery gate: SIGKILL a driver mid-run (the
    supervisor's kill-at-checkpoint hook delivers a real SIGKILL), resume
    from the on-disk checkpoint, and the final digest equals an
    uninterrupted run's. Mismatches CLASSIFY like the sibling
    `_classified_digest_compare` gates and tools/soak.py: the same
    mismatch reproducing across 3 fresh kill+resume cycles is a
    deterministic recovery bug (FAIL); varying mismatches are this box's
    documented pre-crash device-memory scribble poisoning the checkpoint
    (CHANGES.md PR 2 env note) — skip, never a silent pass."""
    import subprocess

    from tests.subproc import run_isolated_json

    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        PYTHONPATH=os.pathsep.join(
            [os.path.abspath(os.path.join(os.path.dirname(__file__), "..")),
             os.environ.get("PYTHONPATH", "")]
        ),
        SHADOW_TPU_TEST_KILL_AT_CHECKPOINT="3",
    )
    prelude = "import jax\njax.config.update('jax_platforms', 'cpu')\n"

    def attempt(idx: int):
        base = tmp_path / f"a{idx}"
        ref = run_isolated_json(
            _KILL_RESUME_SCRIPT, str(base / "ref"), "fresh"
        )
        # the kill leg dies by design (SIGKILL at the 3rd checkpoint):
        # drive it directly — run_isolated would mistake an intentional
        # -9 + empty stdout for an ordinary completed process, and we
        # must also tolerate it dying EARLIER of the box's spontaneous
        # corruption (the resume below recovers either way, from
        # whatever checkpoint landed)
        proc = subprocess.run(
            [sys.executable, "-c", prelude + _KILL_RESUME_SCRIPT,
             str(base / "kill"), "fresh"],
            capture_output=True, text=True, timeout=600, env=env,
        )
        assert proc.returncode != 0, "kill leg unexpectedly survived"
        if not os.path.exists(base / "kill" / "resume.npz"):
            pytest.skip(
                "kill leg died before its first checkpoint landed "
                f"(rc={proc.returncode}): nothing to resume on this box"
            )
        res = run_isolated_json(
            _KILL_RESUME_SCRIPT, str(base / "kill"), "resume"
        )
        return ref, res

    pairs = []
    for i in range(3):
        ref, res = attempt(i)
        if res["digest"] == ref["digest"]:
            assert res["events"] == ref["events"]
            return
        pairs.append((ref["digest"], res["digest"]))
    assert len(set(pairs)) > 1, (
        "kill+resume deterministically diverges (identical mismatch on "
        f"3 fresh cycles): resumed {pairs[0][1]} != reference {pairs[0][0]}"
    )
    pytest.skip(
        f"kill+resume digests mismatched DIFFERENTLY across 3 attempts "
        f"({pairs}): the documented pre-crash device-memory scribble "
        "poisons checkpoints written near a crash (CHANGES.md PR 2 env "
        "note), not a deterministic recovery bug"
    )


_ABORT_EXPORT_SCRIPT = """
import json, os, sys
from shadow_tpu.config.options import ConfigOptions
from shadow_tpu.sim import Simulation

data_dir = sys.argv[1]
cfgd = {
  'general': {'stop_time': '2 s', 'seed': 1, 'heartbeat_interval': None,
              'data_directory': data_dir},
  'network': {'graph': {'type': '1_gbit_switch'}},
  'experimental': {'event_queue_capacity': 16, 'rounds_per_chunk': 8},
  'observability': {'trace': True},
  'faults': {'supervisor': {'snapshot_every_chunks': 1, 'max_retries': 2,
                            'backoff_base_ms': 1}},
  'hosts': {'node': {'count': 12, 'network_node_id': 0,
      'processes': [{'model': 'phold',
                     'model_args': {'population': 2, 'mean_delay': '100 ms',
                                    'size_bytes': 64}}]}},
}
cfg = ConfigOptions.from_dict(cfgd)
sim = Simulation(cfg, world=1)
# force a PERMANENT dispatch failure from chunk 3 on
real = sim.engine.run_chunk
calls = {'n': 0}
def broken(state, params):
    calls['n'] += 1
    if calls['n'] >= 3:
        raise RuntimeError('injected permanent dispatch failure')
    return real(state, params)
sim.engine.run_chunk = broken
rep = sim.run(log=sys.stderr)
sim.write_outputs(report=rep)
print(json.dumps({
    'aborted': rep.get('aborted', False),
    'retries': rep['supervisor']['retries'],
    'rounds': rep['rounds'],
    'have_stats': os.path.exists(os.path.join(data_dir, 'sim-stats.json')),
    'have_trace': os.path.exists(os.path.join(data_dir, 'trace.json')),
}))
"""


def test_permanent_failure_still_exports_prefix(tmp_path):
    """Acceptance: a forced permanent dispatch failure aborts with
    bounded retries AND still writes sim-stats/trace artifacts for the
    completed prefix."""
    from tests.subproc import run_isolated_json

    out = run_isolated_json(_ABORT_EXPORT_SCRIPT, str(tmp_path / "d"))
    assert out["aborted"] is True
    assert out["retries"] == 3  # max_retries(2) + the first attempt
    assert out["rounds"] > 0  # the completed prefix, not an empty run
    assert out["have_stats"] and out["have_trace"]
