"""Timer wheel + sort-free calendar merge (ISSUE 12).

Three layers of gates, mirroring the bucketed-queue/popk precedent:

  1. per-op property sweeps (hypothesis-style seeded randomized
     sequences, no hypothesis dep): wheel push/cancel/pop-due against a
     sorted-list reference model, and the scatter merge against the sort
     merge on random row sets including forced overflow;
  2. engine digest matrix: wheel ON is event-for-event identical
     (digests, events, every drop counter) to wheel OFF across
     echo/phold/tgen x flat/bucketed queue layouts, including a
     spill-forcing tiny wheel and the merge_scatter knob;
  3. checkpoint round-trip + cross-slot migration restore (subprocess-
     isolated: compiled Simulation sequences are this box's documented
     corruption magnet — tests/subproc.py classifies and retries).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shadow_tpu.ops.events import (
    ORDER_MAX,
    pack_order,
    q_len,
)
from shadow_tpu.ops.merge import merge_flat_events, merge_scatter_free
from shadow_tpu.ops.events import make_queue, pop_min, push_one
from shadow_tpu.ops.wheel import (
    make_wheel,
    migrate_wheel,
    resolve_wheel_block,
    wheel_cancel,
    wheel_free,
    wheel_len,
    wheel_next_time,
    wheel_pop_min,
    wheel_push_many,
)
from shadow_tpu.simtime import TIME_MAX
from tests.engine_harness import mk_hosts, run_sim

P = 4  # EVENT_PAYLOAD_WORDS


# --------------------------------------------------------------------------
# 1a. wheel op property sweep vs a sorted-list reference
# --------------------------------------------------------------------------


def test_resolve_wheel_block():
    assert resolve_wheel_block(16) == 4
    assert resolve_wheel_block(8) == 2  # sqrt(8) ~ 2.83 -> divisor 2
    assert resolve_wheel_block(12, 6) == 6
    assert resolve_wheel_block(7) in (1, 7)
    with pytest.raises(ValueError):
        resolve_wheel_block(8, 3)
    with pytest.raises(ValueError):
        resolve_wheel_block(0)


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("slots,block", [(8, 0), (12, 4), (5, 1)])
def test_wheel_ops_match_reference(seed, slots, block):
    """Randomized push / cancel / pop-due sequences: the wheel's visible
    behavior (popped (t, order, kind) sequence, next_time, occupancy,
    cancel hits) must equal a per-host sorted-set reference. The wheel
    never drops (the caller contract masks overflow away via
    wheel_free) — asserted via the dropped lane staying zero."""
    rng = np.random.default_rng(seed)
    h = 4
    w = make_wheel(h, slots, block)
    ref = [set() for _ in range(h)]  # host -> {(t, order, kind)}
    seq = 0
    for _step in range(60):
        op = rng.integers(0, 3)
        if op == 0:  # push (masked to hosts with free slots)
            t = rng.integers(1, 1000, size=h).astype(np.int64)
            kind = rng.integers(0, 7, size=h).astype(np.int32)
            order = np.asarray(
                pack_order(1, np.arange(h), np.full(h, seq))
            )
            seq += 1
            free = np.asarray(wheel_free(w))
            mask = (rng.random(h) < 0.8) & (free > 0)
            w = wheel_push_many(
                w,
                [(
                    jnp.asarray(mask),
                    jnp.asarray(t),
                    jnp.asarray(order),
                    jnp.asarray(kind),
                    jnp.zeros((h, P), jnp.int32),
                )],
            )
            for i in range(h):
                if mask[i]:
                    ref[i].add((int(t[i]), int(order[i]), int(kind[i])))
        elif op == 1:  # pop-due below a random limit
            limit = int(rng.integers(1, 1100))
            w, ev, active = wheel_pop_min(w, jnp.int64(limit))
            ev = jax.device_get(ev)
            active = np.asarray(active)
            for i in range(h):
                due = [e for e in ref[i] if e[0] < limit]
                if due:
                    want = min(due)  # (t, order) lexicographic min
                    assert bool(active[i])
                    got = (int(ev.t[i]), int(ev.order[i]), int(ev.kind[i]))
                    assert got == want, f"host {i}: {got} != {want}"
                    ref[i].remove(want)
                else:
                    assert not bool(active[i])
        else:  # cancel a (sometimes live, sometimes stale) order key
            targets = np.full(h, -1, np.int64)
            for i in range(h):
                if ref[i] and rng.random() < 0.7:
                    targets[i] = sorted(ref[i])[
                        rng.integers(0, len(ref[i]))
                    ][1]
                else:
                    targets[i] = int(
                        pack_order(1, i, 10_000 + int(rng.integers(100)))
                    )
            mask = rng.random(h) < 0.8
            w, found = wheel_cancel(
                w, jnp.asarray(mask), jnp.asarray(targets)
            )
            found = np.asarray(found)
            for i in range(h):
                live = [e for e in ref[i] if e[1] == targets[i]]
                if mask[i] and live:
                    assert bool(found[i])
                    ref[i].remove(live[0])
                else:
                    assert not bool(found[i])
        # invariants after every op
        nt = np.asarray(wheel_next_time(w))
        ln = np.asarray(wheel_len(w))
        for i in range(h):
            want_nt = min((e[0] for e in ref[i]), default=TIME_MAX)
            assert int(nt[i]) == want_nt
            assert int(ln[i]) == len(ref[i])
        assert int(np.asarray(w.dropped).sum()) == 0
        # block caches agree with the slab (the BucketQueue invariant)
        occ = np.asarray((jax.device_get(w.t) != TIME_MAX).sum(axis=1))
        assert (np.asarray(w.bfill).sum(axis=1) == occ).all()


def test_wheel_migrate_roundtrip():
    """Grow and shrink re-seat the same timer multiset (positions are
    unobservable — popping everything yields the identical sequence)."""
    h = 3
    w = make_wheel(h, 6)
    seq = 0
    for t in (50, 30, 90, 10):
        order = pack_order(1, jnp.arange(h), jnp.full((h,), seq))
        seq += 1
        w = wheel_push_many(
            w,
            [(
                jnp.ones((h,), bool),
                jnp.full((h,), t, jnp.int64),
                order,
                jnp.full((h,), 1, jnp.int32),
                jnp.zeros((h, P), jnp.int32),
            )],
        )

    def drain(wheel):
        out = []
        for _ in range(10):
            wheel, ev, active = wheel_pop_min(wheel, jnp.int64(TIME_MAX))
            if not bool(np.asarray(active).any()):
                break
            out.append(
                (np.asarray(ev.t).tolist(), np.asarray(ev.order).tolist())
            )
        return out

    want = drain(w)
    assert drain(migrate_wheel(w, 12)) == want
    assert drain(migrate_wheel(w, 4)) == want  # 4 live timers fit exactly


# --------------------------------------------------------------------------
# 1b. scatter merge vs sort merge property sweep
# --------------------------------------------------------------------------


def _drain_queue(q):
    out = []
    for _ in range(q.t.shape[0] * q.t.shape[1] + 1):
        q, ev, active = pop_min(q, jnp.int64(TIME_MAX))
        if not bool(np.asarray(active).any()):
            break
        out.append((
            np.asarray(ev.t).tolist(),
            np.asarray(ev.order).tolist(),
            np.asarray(ev.kind).tolist(),
        ))
    return out


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
@pytest.mark.parametrize("load", ["light", "overflow"])
def test_merge_scatter_free_matches_sort(seed, load):
    """Random row sets into a random pre-filled queue: the sort-free
    scatter merge must leave a queue whose OBSERVABLE behavior (drain
    order via pop_min, drop counts) is identical to the sort merge's.
    `overflow` forces per-destination counts past the free slots so the
    fallback path (which IS the sort path) must engage — equality then
    covers shed behavior too."""
    rng = np.random.default_rng(seed)
    h, cap = 6, 8
    q = make_queue(h, cap)
    # pre-fill some slots so free ranks are nontrivial
    seq = 0
    for _ in range(int(rng.integers(0, 3))):
        t0 = rng.integers(1, 500, size=h).astype(np.int64)
        order = np.asarray(pack_order(1, np.arange(h), np.full(h, seq)))
        seq += 1
        mask = rng.random(h) < 0.7
        q = push_one(
            q, jnp.asarray(mask), jnp.asarray(t0), jnp.asarray(order),
            jnp.full((h,), 2, jnp.int32), jnp.zeros((h, P), jnp.int32),
        )
    n = 24 if load == "overflow" else 10
    hot = int(rng.integers(0, h))
    dst = rng.integers(0, h, size=n).astype(np.int32)
    if load == "overflow":
        dst[: n // 2] = hot  # slam one destination past its free slots
    t = rng.integers(600, 1000, size=n).astype(np.int64)
    order = np.array(
        [int(pack_order(0, int(rng.integers(0, h)), 1000 + j))
         for j in range(n)], np.int64,
    )
    kind = rng.integers(0, 5, size=n).astype(np.int32)
    payload = rng.integers(0, 100, size=(n, P)).astype(np.int32)
    valid = rng.random(n) < 0.9
    args = (
        jnp.asarray(dst), jnp.asarray(t), jnp.asarray(order),
        jnp.asarray(kind), jnp.asarray(payload), jnp.asarray(valid),
    )
    q_sort = merge_flat_events(q, *args, max_inserts=cap)
    q_scat = merge_scatter_free(q, *args, max_inserts=cap)
    np.testing.assert_array_equal(
        np.asarray(q_sort.dropped), np.asarray(q_scat.dropped)
    )
    assert _drain_queue(q_sort) == _drain_queue(q_scat)
    if load == "overflow":
        assert int(np.asarray(q_sort.dropped).sum()) > 0  # fallback engaged


# --------------------------------------------------------------------------
# 2. engine digest matrix: wheel/merge_scatter ON == OFF
# --------------------------------------------------------------------------

_CASES = {
    "echo": ("udp_echo",
             [dict(host_id=0, name="server", start_time=0,
                   model_args={"role": "server"})]
             + [dict(host_id=i, name=f"c{i}", start_time=0,
                     model_args={"role": "client", "peer": "server",
                                 "interval": "4 ms", "size_bytes": 2000})
                for i in range(1, 5)],
             200_000_000, dict(bw_bits=2_000_000, loss=0.05)),
    "phold": ("phold", mk_hosts(8, {"mean_delay": "20 ms", "population": 3}),
              300_000_000, dict(loss=0.1)),
    "tgen": ("tgen_tcp",
             mk_hosts(5, {"flow_segs": 8, "flows": 2, "cwnd_cap": 8,
                          "rto_min": "100 ms"}),
             2_000_000_000,
             dict(loss=0.05, latency=10_000_000, sends_budget=16)),
}

_DROP_FIELDS = (
    "pkts_sent", "pkts_lost", "pkts_unreachable", "pkts_codel_dropped",
    "pkts_delivered", "pkts_budget_dropped", "monotonic_violations",
)


def _assert_identical(st_a, s_a, st_b, s_b):
    np.testing.assert_array_equal(
        np.asarray(s_a.digest), np.asarray(s_b.digest)
    )
    np.testing.assert_array_equal(
        np.asarray(s_a.events), np.asarray(s_b.events)
    )
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(st_a.queue.dropped)),
        np.asarray(jax.device_get(st_b.queue.dropped)),
    )
    assert int(s_a.rounds) == int(s_b.rounds)
    for f in _DROP_FIELDS:
        np.testing.assert_array_equal(
            np.asarray(getattr(s_a, f)), np.asarray(getattr(s_b, f)),
            err_msg=f,
        )


def _matrix_params():
    out = []
    for case in sorted(_CASES):
        for qb in (0, 8):
            # aligned half runs in tier-1; the cross combos add no code
            # path (wheel routing/pop-merge is layout-independent) and
            # ride the slow mark like the netobs matrix
            marks = () if (qb == 0) == (case != "phold") else (
                pytest.mark.slow,
            )
            out.append(pytest.param(
                case, qb,
                id=f"{case}-{'flat' if qb == 0 else 'bucketed'}",
                marks=marks,
            ))
    return out


@pytest.mark.parametrize("case,qb", _matrix_params())
def test_wheel_on_off_bit_identical(case, qb):
    """The ISSUE acceptance gate: wheel ON (ample slots) is bit-identical
    to OFF — digests, events, drops — and timers really ride the wheel
    (occupancy high-water > 0, zero wheel drops)."""
    model, hosts, stop, kw = _CASES[case]
    st0, s0, _ = run_sim(model, hosts, stop, queue_block=qb, **kw)
    st1, s1, _ = run_sim(
        model, hosts, stop, queue_block=qb, wheel_slots=8, **kw
    )
    _assert_identical(st0, s0, st1, s1)
    assert int(np.asarray(s1.wheel_occ_hwm).max()) > 0
    assert int(np.asarray(jax.device_get(st1.wheel.dropped)).sum()) == 0
    assert s0.wheel_occ_hwm is None  # off path carries no wheel lanes


@pytest.mark.parametrize("case", sorted(_CASES))
def test_wheel_spill_path_bit_identical(case):
    """A one-slot wheel forces spills: results stay bit-identical (the
    spilled timers are queue events exactly as in the off path) and the
    spill counter proves the path ran."""
    model, hosts, stop, kw = _CASES[case]
    st0, s0, _ = run_sim(model, hosts, stop, **kw)
    st1, s1, _ = run_sim(model, hosts, stop, wheel_slots=1, **kw)
    _assert_identical(st0, s0, st1, s1)
    if case != "echo":
        # echo keeps at most ONE pending tick per host — it can never
        # spill a 1-slot wheel; phold (population 3) and tgen
        # (RTO + DELACK + tick) genuinely contend for the slot
        assert int(np.asarray(s1.wheel_spilled).sum()) > 0
    assert int(np.asarray(jax.device_get(st1.wheel.dropped)).sum()) == 0


@pytest.mark.parametrize("case", sorted(_CASES))
def test_merge_scatter_bit_identical(case):
    model, hosts, stop, kw = _CASES[case]
    st0, s0, _ = run_sim(model, hosts, stop, **kw)
    st1, s1, _ = run_sim(model, hosts, stop, merge_scatter=True, **kw)
    _assert_identical(st0, s0, st1, s1)


@pytest.mark.slow
def test_merge_scatter_overflow_fallback_bit_identical():
    """A queue sized to actually overflow under tgen exercises the
    in-jit sort fallback: drops (and everything else) must match the
    sort path exactly."""
    model, hosts, stop, kw = _CASES["tgen"]
    kw = dict(kw, qcap=4, microstep_limit=16)
    st0, s0, _ = run_sim(model, hosts, stop, **kw)
    st1, s1, _ = run_sim(model, hosts, stop, merge_scatter=True, **kw)
    _assert_identical(st0, s0, st1, s1)


def test_wheel_plus_scatter_plus_netobs_reconciles():
    """The flagship combination (bench config 11): wheel + scatter merge
    + network observatory. Digests identical to the plain run AND the
    event-class accounting still reconciles (ec_timer + ec_pkt + ec_app
    == events) — the ec_timer count is exactly the wheel's traffic."""
    model, hosts, stop, kw = _CASES["tgen"]
    st0, s0, _ = run_sim(model, hosts, stop, **kw)
    st1, s1, _ = run_sim(
        model, hosts, stop, wheel_slots=8, merge_scatter=True, netobs=True,
        flow_records=32, **kw
    )
    _assert_identical(st0, s0, st1, s1)
    ec = (
        int(np.asarray(s1.ec_timer).sum())
        + int(np.asarray(s1.ec_pkt).sum())
        + int(np.asarray(s1.ec_app).sum())
    )
    assert ec == int(np.asarray(s1.events).sum())
    assert int(np.asarray(s1.ec_timer).sum()) > 0


def test_wheel_with_integrity_sentinel_clean():
    """The sentinel's wheel-extended guards (slab floor over the wheel
    plane, wheel fill-cache agreement, zero wheel drops) stay quiet on a
    legal run."""
    model, hosts, stop, kw = _CASES["phold"]
    st, s, _ = run_sim(
        model, hosts, stop, wheel_slots=4, integrity=True, **kw
    )
    assert int(np.asarray(s.integrity).sum()) == 0
    assert int(np.asarray(s.iv_round).max()) == -1


def test_wheel_rejects_kway_and_empty_timer_models():
    from shadow_tpu.core.engine import Engine, EngineConfig

    with pytest.raises(ValueError, match="K-way"):
        EngineConfig(
            num_hosts=4, stop_time=1000, queue_capacity=8,
            wheel_slots=4, microstep_events=4,
        )
    with pytest.raises(ValueError, match="wheel_block"):
        EngineConfig(
            num_hosts=4, stop_time=1000, queue_capacity=8,
            wheel_slots=8, wheel_block=3,
        )

    class NoTimers:
        name = "no_timers"

    cfg = EngineConfig(
        num_hosts=4, stop_time=1000, queue_capacity=8, wheel_slots=4
    )
    with pytest.raises(ValueError, match="timer_kinds"):
        Engine(cfg, NoTimers())


def test_config_knobs_parse_and_validate():
    from shadow_tpu.config.options import ConfigError, ExperimentalOptions

    e = ExperimentalOptions.from_dict(
        {"timer_wheel": 16, "timer_wheel_block": 4, "merge_scatter": True}
    )
    assert (e.timer_wheel, e.timer_wheel_block, e.merge_scatter) == (
        16, 4, True
    )
    with pytest.raises(ConfigError, match="timer_wheel_block"):
        ExperimentalOptions.from_dict(
            {"timer_wheel": 16, "timer_wheel_block": 5}
        )
    with pytest.raises(ConfigError, match="microstep_events"):
        ExperimentalOptions.from_dict(
            {"timer_wheel": 16, "microstep_events": 4}
        )
    with pytest.raises(ConfigError, match="timer_wheel"):
        ExperimentalOptions.from_dict({"timer_wheel": -1})


def test_wheel_lanes_priced_by_memory_model():
    """The HBM byte model prices the wheel planes: formula bytes ==
    actual carry-leaf bytes on a built wheel state (satellite 2)."""
    from shadow_tpu.core import lanes
    from shadow_tpu.obs.memory import (
        dims_of_state, lane_plane_bytes, leaf_nbytes,
    )
    from tests.engine_harness import build_sim
    from shadow_tpu.core.engine import Engine

    model, hosts, stop, kw = _CASES["phold"]
    cfg, mdl, params, mstate, events = build_sim(
        model, hosts, stop, wheel_slots=12, wheel_block=4, **kw
    )
    eng = Engine(cfg, mdl)
    state, params = eng.init_state(params, mstate, events, seed=1)
    dims = dims_of_state(cfg, state)
    assert dims["WS"] == 12 and dims["WNB"] == 3
    for path in lanes.STATE_LANES:
        if not path.startswith("wheel."):
            continue
        field = path.split(".", 1)[1]
        leaf = getattr(state.wheel, field)
        assert lane_plane_bytes(path, dims) == leaf_nbytes(leaf), path
    # wheel-off states price the planes as absent
    cfg0, mdl0, params0, mstate0, events0 = build_sim(
        model, hosts, stop, **kw
    )
    eng0 = Engine(cfg0, mdl0)
    state0, _ = eng0.init_state(params0, mstate0, events0, seed=1)
    dims0 = dims_of_state(cfg0, state0)
    assert lane_plane_bytes("wheel.t", dims0) is None
    assert lane_plane_bytes("stats.wheel_spilled", dims0) is None


def test_example_wheel_yaml_parses():
    import os

    from shadow_tpu.config.options import load_config

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cfg = load_config(os.path.join(repo, "examples", "wheel.yaml"))
    assert cfg.experimental.timer_wheel == 4
    assert cfg.observability.network


def test_bench_compare_wheel_gates():
    """The bench_compare satellite: reconciliation drift and wheel drops
    are regressions; spill growth is a warning; losing the block is a
    coverage warning."""
    from tools.bench_compare import compare

    def row(timer=5, spilled=0, dropped=0, wheel=True):
        r = {
            "value": 10.0,
            "counters": {},
            "network": {"event_classes": {
                "timer": timer, "packet": 10, "app": 5, "total": 20,
            }},
        }
        if wheel:
            r["counters"]["wheel"] = {
                "slots": 4, "occupancy_hwm": 2,
                "spilled": spilled, "dropped": dropped,
            }
        return r

    def kinds(old, new):
        return [
            (f["kind"], f["severity"])
            for f in compare(old, new, 0.5, 0.5)
            if f["kind"] == "wheel"
        ]

    assert kinds({"m": row()}, {"m": row()}) == []
    # timer+packet+app != total -> regression
    assert ("wheel", "regression") in kinds(
        {"m": row()}, {"m": row(timer=4)}
    )
    # wheel dropped -> regression
    assert ("wheel", "regression") in kinds(
        {"m": row()}, {"m": row(dropped=3)}
    )
    # spill growth -> warning
    assert ("wheel", "warning") in kinds(
        {"m": row(spilled=0)}, {"m": row(spilled=7)}
    )
    # block lost -> coverage warning
    assert ("wheel", "warning") in kinds(
        {"m": row()}, {"m": row(wheel=False)}
    )


def test_net_report_breaks_out_wheel(capsys):
    """The net_report satellite: with a wheel{} block in sim-stats the
    verdict line breaks out occupancy and spills instead of arguing for
    the rebuild the run already has."""
    from tools.net_report import print_report

    net = {"event_classes": {
        "timer": 11, "packet": 67, "app": 22, "total": 100,
        "timer_share": 0.11, "packet_share": 0.67,
    }}
    print_report({"wheel": {
        "slots": 4, "occupancy_hwm": 2, "spilled": 0, "dropped": 0,
    }}, net)
    out = capsys.readouterr().out
    assert "ride the device wheel" in out
    assert "occupancy hwm 2/4 slots" in out
    print_report({}, net)
    out2 = capsys.readouterr().out
    assert "experimental.timer_wheel" in out2


# --------------------------------------------------------------------------
# 3. checkpoint round-trip (subprocess-isolated: compiled Simulation
#    sequences are the documented corruption magnet on this box)
# --------------------------------------------------------------------------

_CKPT_CHILD = r"""
import json, os, sys
import numpy as np
import jax
jax.config.update("jax_enable_x64", True)

from shadow_tpu.config.options import ConfigOptions
from shadow_tpu.core.checkpoint import (
    CheckpointError, load_checkpoint, save_checkpoint,
)
from shadow_tpu.sim import Simulation

tmp = sys.argv[1]

def build(wheel_slots, stop_s=2, extra=None):
    d = {
        "general": {"stop_time": f"{stop_s} s", "seed": 7,
                     "progress": False,
                     "data_directory": os.path.join(tmp, "out")},
        "network": {"graph": {"type": "1_gbit_switch"}},
        "hosts": {
            "h": {"count": 6, "network_node_id": 0,
                   "processes": [{"model": "phold",
                                  "model_args": {"mean_delay": "40 ms",
                                                 "population": 3}}]},
        },
        "experimental": {"timer_wheel": wheel_slots,
                          "rounds_per_chunk": 4, **(extra or {})},
    }
    return Simulation(ConfigOptions.from_dict(d))

# uninterrupted reference
sim_ref = build(6)
sim_ref.run()
ref = sim_ref.stats_report()

# interrupted: run a few chunks, checkpoint, resume in a FRESH sim
sim_a = build(6)
for _ in range(3):
    sim_a.state = sim_a.engine.run_chunk(sim_a.state, sim_a.params)
path = save_checkpoint(os.path.join(tmp, "ck"), sim_a)

sim_b = build(6)
load_checkpoint(path, sim_b)
sim_b.run()
got = sim_b.stats_report()
assert got["determinism_digest"] == ref["determinism_digest"], (
    got["determinism_digest"], ref["determinism_digest"])
assert got["events_processed"] == ref["events_processed"]

# cross-slot migration restore: resume the same checkpoint at S'=12
sim_c = build(12)
load_checkpoint(path, sim_c)
sim_c.run()
got_c = sim_c.stats_report()
assert got_c["determinism_digest"] == ref["determinism_digest"], (
    got_c["determinism_digest"], ref["determinism_digest"])

# wheel on/off cross-restore refuses loudly
sim_d = build(0)
try:
    load_checkpoint(path, sim_d)
except CheckpointError:
    pass
else:
    raise AssertionError("wheel->no-wheel restore did not refuse")

print("CKPT_OK")
"""


@pytest.mark.slow
def test_wheel_checkpoint_roundtrip(tmp_path):
    from tests.subproc import run_isolated

    proc = run_isolated(_CKPT_CHILD, str(tmp_path), timeout=420)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "CKPT_OK" in proc.stdout
