"""Unix-domain socket tests (reference host/descriptor/socket/unix/ +
abstract_unix_ns.rs test families)."""

from __future__ import annotations

import pytest

from shadow_tpu.host import CpuHost, FileState, HostConfig
from shadow_tpu.host.unix import UnixStreamSocket

SEC = 1_000_000_000


def test_socketpair_duplex_and_eof():
    a, b = UnixStreamSocket.make_pair()
    assert a.write(b"x" * 10) == 10
    assert b.read(4) == b"x" * 4
    assert b.write(b"reply") == 5
    assert a.read(64) == b"reply"
    a.close()
    assert b.read(64) == b"xxxxxx"  # drains remaining buffered bytes
    assert b.read(64) == b""  # then EOF
    assert b.state & FileState.HUP
    with pytest.raises(BrokenPipeError):
        b.write(b"dead")


def test_socketpair_backpressure():
    a, b = UnixStreamSocket.make_pair()
    total = 0
    while (n := a.write(b"y" * 65536)) is not None:
        total += n
    assert not (a.state & FileState.WRITABLE)
    b.read(1000)
    assert a.state & FileState.WRITABLE


def test_abstract_namespace_listen_connect():
    ns: dict = {}
    lst = UnixStreamSocket()
    lst.bind_abstract(ns, "svc")
    lst.listen()
    with pytest.raises(OSError):
        UnixStreamSocket().bind_abstract(ns, "svc")  # EADDRINUSE
    cli = UnixStreamSocket()
    cli.connect_to(lst)
    srv = lst.accept()
    assert srv is not None
    cli.write(b"req")
    assert srv.read(16) == b"req"
    srv.write(b"resp")
    assert cli.read(16) == b"resp"
    lst.close()
    assert "svc" not in ns


def test_unix_program_end_to_end():
    h = CpuHost(HostConfig(name="h", ip="10.0.0.1", seed=1))
    from shadow_tpu.programs import get_program

    p = h.spawn(get_program("unix_echo_pair"))
    h.execute(1 * SEC)
    assert p.exit_code == 0, b"".join(p.stderr)
    assert b"unix ok: hello-unix" in b"".join(p.stdout)


def test_reconnect_raises_eisconn():
    ns: dict = {}
    lst = UnixStreamSocket()
    lst.bind_abstract(ns, "svc")
    lst.listen()
    cli = UnixStreamSocket()
    cli.connect_to(lst)
    with pytest.raises(OSError, match="EISCONN"):
        cli.connect_to(lst)


def test_unix_shutdown_write_delivers_eof():
    h = CpuHost(HostConfig(name="h", ip="10.0.0.1", seed=1))
    got = []

    def prog(ctx):
        a, b = yield ("socketpair",)
        yield ("write", a, b"bye")
        yield ("shutdown", a)
        got.append((yield ("read", b, 16)))
        got.append((yield ("read", b, 16)))  # EOF after drain
        got.append((yield ("getpeername", b)))
        yield ("exit", 0)

    p = h.spawn(prog)
    h.execute(SEC)
    assert p.exit_code == 0, b"".join(p.stderr)
    assert got == [b"bye", b"", ("unix", 0)]


def test_connect_unbound_name_refused():
    h = CpuHost(HostConfig(name="h", ip="10.0.0.1", seed=1))
    errs = []

    def prog(ctx):
        fd = yield ("socket", "unix")
        try:
            yield ("connect", fd, "@nobody")
        except OSError as e:
            errs.append(str(e))
        yield ("exit", 0)

    h.spawn(prog)
    h.execute(1 * SEC)
    assert errs and "ECONNREFUSED" in errs[0]
