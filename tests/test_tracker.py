"""Per-socket and per-interface byte/packet counters (VERDICT r4 #6;
reference host/tracker.c:24-80 — per-host heartbeats carrying per-socket
and per-interface in/out counters)."""

from __future__ import annotations

import json
import os

from shadow_tpu.config.options import ConfigOptions
from shadow_tpu.cosim import HybridSimulation


def _cfg(tmp_path, stop="6 s"):
    return ConfigOptions.from_dict(
        {
            "general": {
                "stop_time": stop,
                "seed": 9,
                "data_directory": str(tmp_path / "data"),
                "heartbeat_interval": "1 s",
            },
            "network": {"graph": {"type": "1_gbit_switch"}},
            "hosts": {
                "srv": {
                    "network_node_id": 0,
                    "processes": [{"path": "udp_echo_server",
                                   "args": ["port=9000"]}],
                },
                "cli": {
                    "network_node_id": 0,
                    "processes": [{
                        "path": "udp_blast",
                        # spread over ~3.6 sim-s so several 1 s heartbeat
                        # intervals see traffic
                        "args": ["server=srv", "port=9000", "count=12",
                                 "interval_ns=300000000"],
                        "expected_final_state": {"exited": 0},
                    }],
                },
            },
        }
    )


def test_per_socket_and_interface_counters(tmp_path):
    sim = HybridSimulation(_cfg(tmp_path), world=1)
    report = sim.run(progress=False)
    assert report["process_failures"] == 0
    data = sim.write_outputs(report=report)

    cli = json.load(open(os.path.join(data, "hosts", "cli",
                                      "host-stats.json")))
    srv = json.load(open(os.path.join(data, "hosts", "srv",
                                      "host-stats.json")))

    # interface split: the blast rides eth0, not loopback
    assert cli["interfaces"]["eth0"]["tx_pkts"] >= 12
    assert cli["interfaces"]["eth0"]["tx_bytes"] > 0
    assert cli["interfaces"]["lo"]["tx_pkts"] == 0
    assert srv["interfaces"]["eth0"]["rx_pkts"] >= 12

    # per-socket attribution: the client's UDP socket accounts its blast
    # and the echoes; the server's bound socket mirrors it
    cli_socks = [s for s in cli["sockets"] if s["proto"] == "udp"]
    assert cli_socks and any(s["tx_pkts"] >= 12 for s in cli_socks)
    srv_socks = [s for s in srv["sockets"] if s["local"].endswith(":9000")]
    assert srv_socks
    assert srv_socks[0]["rx_pkts"] >= 12 and srv_socks[0]["tx_pkts"] >= 12

    # per-heartbeat-interval deltas were recorded and sum to <= cumulative
    assert cli["heartbeats"], "no tracker heartbeats recorded"
    hb_tx = sum(
        h["interfaces"]["eth0"]["tx_pkts"] for h in cli["heartbeats"]
    )
    assert 0 < hb_tx <= cli["interfaces"]["eth0"]["tx_pkts"]
    # interval records carry socket rows only when traffic moved
    busy = [h for h in cli["heartbeats"] if h["sockets"]]
    assert busy and all(
        s["tx_pkts"] or s["rx_pkts"] for h in busy for s in h["sockets"]
    )


def test_closed_tcp_socket_keeps_its_counters():
    """A TCP data socket that fully closes mid-run must still appear in
    the tracker totals (TcpSocket.close bypasses the base-class close;
    the capture hook lives at netns.unbind, the shared teardown point)."""
    import os

    import pytest

    from tests.subproc import native_plane_skip_reason

    # real-binary leg: the shim-cannot-load (exit-97) environment skips
    # with probe evidence instead of hard-F'ing on exit_code asserts —
    # the same classification every other native-gated module uses
    # (tests/subproc.py; this leg was the one PR 8 missed)
    _skip = native_plane_skip_reason()
    if _skip is not None:
        pytest.skip(_skip)

    from shadow_tpu.host import CpuHost, HostConfig
    from shadow_tpu.host.network import CpuNetwork
    from shadow_tpu.native_plane import spawn_native

    repo = os.path.join(os.path.dirname(__file__), "..")
    tcp_stream = os.path.join(repo, "native", "build", "test_tcp_stream")
    hosts = [
        CpuHost(HostConfig(name=f"h{i}", ip=f"10.0.0.{i + 1}", seed=7,
                           host_id=i))
        for i in range(2)
    ]
    net = CpuNetwork(hosts, latency_ns=lambda s, d: 10_000_000)
    srv = spawn_native(hosts[0], [tcp_stream, "server", "9000"])
    cli = spawn_native(
        hosts[1], [tcp_stream, "10.0.0.1", "9000", "40000"],
        start_time=20_000_000,
    )
    net.run(30_000_000_000)
    assert srv.exit_code == 0 and cli.exit_code == 0
    for h in hosts:
        socks = h.socket_stats()
        tcp_rows = [s for s in socks if s["proto"] == "tcp"
                    and (s["tx_bytes"] or s["rx_bytes"])]
        assert tcp_rows, f"{h.name}: TCP socket counters vanished at close"
    # the client pushed 40000 payload bytes; its socket's tx_bytes must
    # cover payload + headers on SOME recorded socket
    cli_rows = [s for s in hosts[1].socket_stats() if s["proto"] == "tcp"]
    assert max(s["tx_bytes"] for s in cli_rows) >= 40000


def test_parse_shadow_aggregates_network_totals(tmp_path):
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from tools.parse_shadow import parse_data_dir

    sim = HybridSimulation(_cfg(tmp_path), world=1)
    report = sim.run(progress=False)
    data = sim.write_outputs(report=report)
    out = parse_data_dir(data)
    t = out["network_totals"]
    assert t["sockets"] >= 2
    assert t["per_socket_sum"]["tx_pkts"] >= 10  # blast + echoes
    assert t["per_interface_sum"]["eth0"]["tx_bytes"] > 0


def test_packet_breadcrumbs_name_the_drop_site(tmp_path):
    """VERDICT r4 #9 (reference packet.rs:16-39): with breadcrumbs on, a
    dropped packet's drop site is identifiable — here a client blasting a
    port nobody listens on produces rcv_no_listener drops whose trails
    show the full hop sequence."""
    cfg = ConfigOptions.from_dict(
        {
            "general": {"stop_time": "2 s", "seed": 3,
                        "data_directory": str(tmp_path / "data")},
            "network": {"graph": {"type": "1_gbit_switch"}},
            "experimental": {"packet_breadcrumbs": True},
            "hosts": {
                "srv": {
                    "network_node_id": 0,
                    # server listens on 9000; client blasts 9999
                    "processes": [{"path": "udp_echo_server",
                                   "args": ["port=9000"]}],
                },
                "cli": {
                    "network_node_id": 0,
                    "processes": [{
                        "path": "udp_blast",
                        "args": ["server=srv", "port=9999", "count=4"],
                    }],
                },
            },
        }
    )
    sim = HybridSimulation(cfg, world=1)
    report = sim.run(progress=False)
    data = sim.write_outputs(report=report)
    srv = json.load(open(os.path.join(data, "hosts", "srv",
                                      "host-stats.json")))
    drops = srv.get("packet_drops", [])
    assert len(drops) >= 4
    d = drops[0]
    assert d["dropped_at"] == "rcv_no_listener"
    assert d["dst"].endswith(":9999")
    statuses = [st for _, st in d["trail"]]
    # the full path is readable: send -> receive -> drop site
    assert statuses[0].startswith("snd_cli")
    assert any(st.startswith("rcv_srv") for st in statuses)
    assert statuses[-1] == "rcv_no_listener"


def test_breadcrumbs_off_by_default_zero_cost(tmp_path):
    sim = HybridSimulation(_cfg(tmp_path), world=1)
    report = sim.run(progress=False)
    data = sim.write_outputs(report=report)
    cli = json.load(open(os.path.join(data, "hosts", "cli",
                                      "host-stats.json")))
    assert "packet_drops" not in cli
