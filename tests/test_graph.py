"""Graph subsystem tests (reference: gml-parser tests + graph/mod.rs routing
semantics: shortest-path latency, composed path loss, direct-edge mode,
IP assignment skipping .0/.255)."""

import numpy as np
import pytest

from shadow_tpu.config.options import GraphOptions
from shadow_tpu.net.graph import (
    GraphError,
    IpAssignment,
    build_graph,
    load_graph,
    parse_gml,
)

TRIANGLE = """
# a comment
graph [
  directed 0
  node [ id 0 host_bandwidth_down "100 Mbit" host_bandwidth_up "10 Mbit" ]
  node [ id 1 ]
  node [ id 7 label "c" ]
  edge [ source 0 target 1 latency "10 ms" packet_loss 0.1 ]
  edge [ source 1 target 7 latency "10 ms" packet_loss 0.1 ]
  edge [ source 0 target 7 latency "50 ms" packet_loss 0.0 ]
]
"""


def test_parse_gml_structure():
    g = parse_gml(TRIANGLE)
    assert not g["directed"]
    assert [n["id"] for n in g["nodes"]] == [0, 1, 7]
    assert g["nodes"][2]["label"] == "c"
    assert len(g["edges"]) == 3


def test_shortest_path_latency_and_loss():
    g = build_graph(TRIANGLE)
    i0, i1, i7 = g.node_index(0), g.node_index(1), g.node_index(7)
    # 0->7: two-hop path (20ms) beats direct edge (50ms)
    assert g.lat_ns[i0, i7] == 20_000_000
    # loss composes: 1 - 0.9*0.9
    assert g.loss[i0, i7] == pytest.approx(1 - 0.9 * 0.9, abs=1e-6)
    assert g.lat_ns[i0, i1] == 10_000_000
    assert g.loss[i0, i1] == pytest.approx(0.1, abs=1e-6)
    # symmetric (undirected)
    assert np.array_equal(g.lat_ns, g.lat_ns.T)
    # no self-edge => same-node pairs cannot route (reference requires a
    # self-loop per node, graph/mod.rs:210-216) and the synthetic Dijkstra
    # zero diagonal must NOT leak into the lookahead bound
    assert g.lat_ns[i0, i0] == -1 and g.loss[i0, i0] == 0
    assert g.min_latency_ns == 10_000_000  # smallest REAL path, not the diagonal
    assert g.bw_down_bits[i0] == 100_000_000 and g.bw_up_bits[i0] == 10_000_000


def test_direct_edge_mode():
    g = build_graph(TRIANGLE, use_shortest_path=False)
    i0, i7 = g.node_index(0), g.node_index(7)
    assert g.lat_ns[i0, i7] == 50_000_000  # no multi-hop routing
    assert g.loss[i0, i7] == 0.0


def test_unreachable_is_minus_one():
    gml = """
    graph [ directed 0
      node [ id 0 ] node [ id 1 ] node [ id 2 ]
      edge [ source 0 target 1 latency "5 ms" ]
    ]
    """
    g = build_graph(gml)
    assert g.lat_ns[g.node_index(0), g.node_index(2)] == -1
    assert g.lat_ns[g.node_index(0), g.node_index(1)] == 5_000_000


def test_directed_graph_asymmetric():
    gml = """
    graph [ directed 1
      node [ id 0 ] node [ id 1 ]
      edge [ source 0 target 1 latency "5 ms" ]
    ]
    """
    g = build_graph(gml)
    assert g.lat_ns[0, 1] == 5_000_000
    assert g.lat_ns[1, 0] == -1


def test_self_edge_routes_loopback():
    gml = """
    graph [ directed 0
      node [ id 0 ]
      edge [ source 0 target 0 latency "2 ms" packet_loss 0.25 ]
    ]
    """
    g = build_graph(gml)
    assert g.lat_ns[0, 0] == 2_000_000
    assert g.loss[0, 0] == pytest.approx(0.25)


def test_parallel_edges_keep_lowest_latency():
    gml = """
    graph [ directed 0
      node [ id 0 ] node [ id 1 ]
      edge [ source 0 target 1 latency "9 ms" packet_loss 0.5 ]
      edge [ source 0 target 1 latency "3 ms" ]
    ]
    """
    g = build_graph(gml)
    assert g.lat_ns[0, 1] == 3_000_000
    assert g.loss[0, 1] == 0.0


def test_builtin_one_gbit_switch():
    g = load_graph(GraphOptions(type="1_gbit_switch"))
    assert g.num_nodes == 1
    assert g.lat_ns[0, 0] == 1_000_000
    assert g.bw_down_bits[0] == 1_000_000_000


def test_gml_errors():
    with pytest.raises(GraphError, match="no nodes"):
        build_graph("graph [ directed 0 ]")
    with pytest.raises(GraphError, match="missing latency"):
        build_graph("graph [ node [ id 0 ] node [ id 1 ] edge [ source 0 target 1 ] ]")
    with pytest.raises(GraphError, match="unknown node"):
        build_graph("graph [ node [ id 0 ] edge [ source 0 target 9 latency \"1 ms\" ] ]")
    with pytest.raises(GraphError, match="duplicate node"):
        build_graph("graph [ node [ id 0 ] node [ id 0 ] ]")


def test_ip_assignment():
    ips = IpAssignment()
    a = ips.assign(0)
    b = ips.assign(1)
    assert ips.ip_of(0) == "11.0.0.1" and ips.ip_of(1) == "11.0.0.2"
    assert a != b
    assert ips.host_of("11.0.0.2") == 1
    ips2 = IpAssignment()
    ips2.assign_manual(5, "11.0.0.1")
    assert ips2.assign(6) != int(np.int64(0xB000001))  # skips taken address
    assert ips2.ip_of(6) == "11.0.0.2"
    with pytest.raises(GraphError, match="duplicate ip"):
        ips2.assign_manual(7, "11.0.0.1")


def test_ip_assignment_skips_0_and_255():
    ips = IpAssignment()
    seen = {ips.assign(i) & 0xFF for i in range(600)}
    assert 0 not in seen and 255 not in seen


def test_large_random_graph_matches_floyd_warshall():
    rng = np.random.default_rng(0)
    n = 40
    lines = ["graph [ directed 0"]
    for i in range(n):
        lines.append(f"  node [ id {i} ]")
    edges = set()
    for _ in range(120):
        a, b = rng.integers(0, n, 2)
        if a == b or (min(a, b), max(a, b)) in edges:
            continue
        edges.add((min(a, b), max(a, b)))
        ms = int(rng.integers(1, 100))
        lines.append(f'  edge [ source {a} target {b} latency "{ms} ms" ]')
    lines.append("]")
    g = build_graph("\n".join(lines))
    # oracle: Floyd-Warshall on the direct-edge matrix
    inf = np.int64(1) << 50
    d = np.where(g.lat_ns >= 0, g.lat_ns, inf)
    dd = build_graph("\n".join(lines), use_shortest_path=False).lat_ns
    d = np.where(dd >= 0, dd, inf)
    np.fill_diagonal(d, 0)
    for k in range(n):
        d = np.minimum(d, d[:, k : k + 1] + d[k : k + 1, :])
    expect = np.where(d >= inf, -1, d)
    # no node in this random graph has a self-edge, so every diagonal entry
    # is unreachable (the synthetic zero path must not leak through)
    np.fill_diagonal(expect, -1)
    np.testing.assert_array_equal(g.lat_ns, expect)


def test_edge_jitter_parsed_and_composed():
    from shadow_tpu.net.graph import build_graph

    g = build_graph("""
graph [
  directed 0
  node [ id 0 ]
  node [ id 1 ]
  node [ id 2 ]
  edge [ source 0 target 1 latency "10 ms" jitter "2 ms" ]
  edge [ source 1 target 2 latency "10 ms" jitter "3 ms" ]
]
""")
    a, b, c = (g.node_index(i) for i in (0, 1, 2))
    assert g.jitter_ns[a, b] == 2_000_000
    assert g.jitter_ns[a, c] == 5_000_000  # composed along the path
    assert g.has_jitter
    # lookahead bound = min over pairs of (latency - jitter amplitude); the
    # 1<->2 edge (10 ms - 3 ms) is the binding pair, not 0<->1 (10 - 2)
    assert g.min_latency_ns == 7_000_000


def test_edge_jitter_must_be_below_latency():
    import pytest

    from shadow_tpu.net.graph import GraphError, build_graph

    with pytest.raises(GraphError):
        build_graph("""
graph [
  directed 0
  node [ id 0 ]
  node [ id 1 ]
  edge [ source 0 target 1 latency "1 ms" jitter "1 ms" ]
]
""")


def test_multinode_min_latency_sets_window_size():
    """A 2-node 50 ms graph must yield ~50 ms scheduling windows — the core
    conservative-PDES perf lever (reference runahead.rs:5-13: round length =
    min path latency). Regression guard for the zero-diagonal bug that
    collapsed every multi-node window to the 1 ms runahead floor."""
    from shadow_tpu.config.options import ConfigOptions
    from shadow_tpu.sim import Simulation

    gml = """
graph [
  directed 0
  node [ id 0 ]
  node [ id 1 ]
  edge [ source 0 target 1 latency "50 ms" ]
]
"""
    cfg = ConfigOptions.from_dict(
        {
            "general": {"stop_time": "1 s", "seed": 3},
            "network": {"graph": {"type": "gml", "inline": gml}},
            "hosts": {
                "a": {
                    "network_node_id": 0,
                    "processes": [{"model": "udp_echo",
                                   "model_args": {"role": "server"}}],
                },
                "b": {
                    "network_node_id": 1,
                    "processes": [{"model": "udp_echo",
                                   "model_args": {"role": "client",
                                                  "peer": "a",
                                                  "interval": "100 ms"}}],
                },
            },
        }
    )
    g = Simulation(cfg, world=1)
    assert g.graph.min_latency_ns == 50_000_000
    report = g.run(progress=False)
    # 1 s of sim time at 50 ms windows: ~20 rounds (+ a couple of boot /
    # shutdown rounds). The bug produced ~1000 rounds (1 ms floor).
    assert report["rounds"] <= 30, report["rounds"]
    assert report["packets_delivered"] > 0


def test_two_hosts_on_selfloopless_node_rejected():
    """>= 2 hosts on a node with no self-loop cannot exchange packets; sim
    setup must reject the config (reference requires a self-loop per node,
    graph/mod.rs:210-216)."""
    import pytest as _pytest

    from shadow_tpu.config.options import ConfigError, ConfigOptions
    from shadow_tpu.sim import Simulation

    gml = """
graph [
  directed 0
  node [ id 0 ]
  node [ id 1 ]
  edge [ source 0 target 1 latency "10 ms" ]
]
"""
    cfg = ConfigOptions.from_dict(
        {
            "general": {"stop_time": "1 s"},
            "network": {"graph": {"type": "gml", "inline": gml}},
            "hosts": {
                "x": {
                    "count": 2,
                    "network_node_id": 0,
                    "processes": [{"model": "udp_echo",
                                   "model_args": {"role": "server"}}],
                },
            },
        }
    )
    with _pytest.raises(ConfigError, match="self-loop"):
        Simulation(cfg, world=1)
