"""SACK, delayed ACK, and Nagle in the CPU-plane TCP machine (VERDICT r4
missing #3; reference tcp.c:151-177 selectiveACKs, tcp.c:1254,2014 delayed
ACK). Style mirrors /root/reference/src/lib/tcp/src/tests: two TcpState
endpoints over a deterministic wire with scripted drops."""

from __future__ import annotations

from shadow_tpu.tcp import TcpConfig, TcpState
from shadow_tpu.tcp.segment import ACK, SYN

from tests.tcp_harness import MS, Wire, handshake


def _drain(tcp: TcpState) -> bytes:
    out = bytearray()
    while True:
        d = tcp.recv(1 << 20)
        if not d:
            break
        out += d
    return bytes(out)


def _pure_acks_from(wire: Wire, who: str):
    return [
        (t, s)
        for t, snd, s in wire.sent
        if snd == who and s.flags == ACK and not s.payload
    ]


def _data_resends(wire: Wire, who: str) -> int:
    seqs = [s.seq for _, snd, s in wire.sent if snd == who and s.payload]
    return len(seqs) - len(set(seqs))


def test_sack_negotiated_on_syn():
    client, server, wire = handshake()
    assert client.sack_ok and server.sack_ok


def test_sack_disabled_when_peer_lacks_it():
    client, server, wire = handshake(cfg_server=TcpConfig(sack=False))
    assert not client.sack_ok and not server.sack_ok


def test_mid_flow_loss_selective_retransmit():
    """Drop one mid-flow data segment: the receiver SACKs the later ranges
    and the sender retransmits ONLY the hole — one segment, not the window
    (reference tcp.c:151-177 selectiveACKs)."""
    dropped = []

    def drop(idx, sender, seg):
        if sender == "a" and seg.payload and not dropped:
            nth = sum(
                1 for _, s, x in wire.sent[:idx] if s == "a" and x.payload
            )
            if nth == 3:  # the 4th data segment, exactly once
                dropped.append(seg)
                return True
        return False

    client, server, wire = handshake(drop=drop)
    payload = bytes(range(256)) * 40  # 10240 B = 8 segments at mss 1460
    client.send(payload)
    wire.run(until=lambda: server.rcv_buf.readable() == len(payload))
    assert _drain(server) == payload
    assert dropped, "the drop hook never fired"
    # dup ACKs carried SACK blocks describing the post-hole data
    assert any(s.sack for _, snd, s in wire.sent if snd == "b"), (
        "receiver never advertised SACK blocks"
    )
    # recovery resent exactly the hole
    assert _data_resends(wire, "a") == 1
    assert client.retransmits == 1


def _two_hole_drop_script(wire_ref):
    """Drop data segments #3 and #10 (two separated holes) and let only the
    first two post-loss pure ACKs through — the sender's scoreboard fills
    (when SACK is on) but the 3-dup-ack fast retransmit never arms, so
    recovery must go through the RTO."""
    state = {"dropped": set(), "acks_after_loss": 0}

    def drop(idx, sender, seg):
        if not wire_ref:  # still inside the handshake helper's own run
            return False
        wire = wire_ref[0]
        if sender == "a" and seg.payload:
            nth = sum(
                1 for _, s, x in wire.sent[:idx] if s == "a" and x.payload
            )
            if nth in (2, 9) and nth not in state["dropped"]:
                state["dropped"].add(nth)
                return True
        if (
            sender == "b"
            and state["dropped"]
            and seg.flags == ACK
            and not seg.payload
        ):
            # suppress only DUPLICATE acks (unchanged ack field) beyond the
            # first two — acks that advance must flow or nothing finishes
            if seg.ack in state.setdefault("seen_acks", set()):
                state["acks_after_loss"] += 1
                return state["acks_after_loss"] > 2
            state["seen_acks"].add(seg.ack)
        return False

    return drop


def test_rto_with_sack_is_selective_repeat():
    """Two holes, dup-ACK train suppressed: after the RTO rewind the SACK
    scoreboard turns go-back-N into selective repeat — exactly the two lost
    segments are resent, nothing the peer already holds."""
    wire_ref = []
    client, server, wire = handshake(drop=_two_hole_drop_script(wire_ref))
    wire_ref.append(wire)
    payload = b"\xab" * (1460 * 16)
    client.send(payload)
    wire.run(until=lambda: server.rcv_buf.readable() == len(payload))
    assert _drain(server) == payload
    assert _data_resends(wire, "a") == 2  # the two holes, nothing else


def test_rto_without_sack_resends_held_data():
    """Control: the identical drop script with SACK disabled resends data
    the receiver already buffered (go-back-N waste) — the waste SACK
    removes. The cumulative-ACK jumps bound it, so the margin is small but
    strictly larger than the SACK run."""
    wire_ref = []
    client, server, wire = handshake(
        cfg=TcpConfig(sack=False), drop=_two_hole_drop_script(wire_ref)
    )
    wire_ref.append(wire)
    payload = b"\xcd" * (1460 * 16)
    client.send(payload)
    wire.run(until=lambda: server.rcv_buf.readable() == len(payload))
    assert _drain(server) == payload
    assert _data_resends(wire, "a") >= 3  # resent at least one held range


def test_delayed_ack_coalesces_pairs():
    """Two back-to-back segments produce ONE immediate ACK; a lone
    segment's ACK is held until the delack timer fires."""
    client, server, wire = handshake()
    base = len(_pure_acks_from(wire, "b"))
    client.send(b"x" * 2920)  # exactly 2 mss-sized segments
    wire.run(until=lambda: server.rcv_buf.readable() == 2920)
    wire.run()  # settle
    pair_acks = _pure_acks_from(wire, "b")[base:]
    assert len(pair_acks) == 1
    t_mid = wire.now
    client.send(b"y" * 100)  # lone sub-mss segment
    wire.run(until=lambda: server.rcv_buf.readable() == 3020)
    wire.run()
    late = [t for t, _ in _pure_acks_from(wire, "b") if t > t_mid]
    assert late, "held ACK never fired"
    # it fired via the delack timer: arrival (+10 ms wire) + 40 ms hold
    assert late[0] >= t_mid + 10 * MS + 40 * MS
    assert _drain(server) == b"x" * 2920 + b"y" * 100


def test_delayed_ack_disabled_acks_immediately():
    """Without delayed ACK a LONE segment is acked at arrival time, not
    after the 40 ms delack hold (contrast with the coalescing test)."""
    cfg = TcpConfig(delayed_ack=False)
    client, server, wire = handshake(cfg=cfg)
    t0 = wire.now
    client.send(b"x" * 100)  # lone sub-mss segment
    wire.run(until=lambda: server.rcv_buf.readable() == 100)
    wire.run()
    late = [t for t, _ in _pure_acks_from(wire, "b") if t > t0]
    assert late and late[0] <= t0 + 10 * MS  # at arrival (+wire latency)


def test_nagle_holds_small_tail():
    cfg = TcpConfig(nagle=True, delayed_ack=False)
    client, server, wire = handshake(cfg=cfg)
    client.send(b"A" * 1460)
    client.poll_segments(wire.now)  # full segment departs
    client.send(b"B" * 10)
    held = client.poll_segments(wire.now)
    assert not any(s.payload for s in held), "Nagle failed to hold the tail"
    wire.run(until=lambda: server.rcv_buf.readable() == 1470)
    assert _drain(server) == b"A" * 1460 + b"B" * 10


def test_nodelay_sends_small_immediately():
    cfg = TcpConfig(nagle=False, delayed_ack=False)
    client, server, wire = handshake(cfg=cfg)
    client.send(b"A" * 1460)
    client.poll_segments(wire.now)
    client.send(b"B" * 10)
    now = client.poll_segments(wire.now)
    assert any(len(s.payload) == 10 for s in now)
    wire.run(until=lambda: server.rcv_buf.readable() == 1470)
    assert _drain(server) == b"A" * 1460 + b"B" * 10


def test_autotuned_buffers_beat_fixed_small_buffers():
    """VERDICT r4 #10: a receive-window-limited transfer completes faster
    with autotuning (the buffer doubles as the sender keeps it full) than
    with the same small buffer fixed."""
    data = b"\x5a" * (300 * 1024)

    def run(autotune: bool) -> int:
        cfg = TcpConfig(
            recv_buf=8 * 1024, send_buf=512 * 1024,
            autotune=autotune, buf_max=1024 * 1024, delayed_ack=False,
        )
        client, server, wire = handshake(cfg=cfg)
        off = 0
        while True:
            off += client.send(data[off:])
            got = server.rcv_buf.readable()
            if got:
                server.recv(1 << 20)  # drain so the window reopens
            if off >= len(data) and server.segs_received and not wire.step():
                break
            if not wire.step() and off >= len(data):
                break
        return wire.now

    t_fixed = run(False)
    t_auto = run(True)
    assert t_auto < t_fixed * 0.6, (t_auto, t_fixed)


def test_tcp_knobs_flow_from_config_to_sockets(tmp_path):
    """The host-level TCP options cascade into every socket's TcpConfig
    (reference HostDefaultOptions socket buffer knobs)."""
    from shadow_tpu.config.options import ConfigError, ConfigOptions
    from shadow_tpu.net.graph import load_graph
    from shadow_tpu.sim import expand_hosts_hybrid

    cfg = ConfigOptions.from_dict(
        {
            "general": {"stop_time": "1 s"},
            "network": {"graph": {"type": "1_gbit_switch"}},
            "host_option_defaults": {"tcp_send_buffer": "64 KiB",
                                     "tcp_nagle": True},
            "hosts": {
                "m": {
                    "network_node_id": 0,
                    "host_options": {"tcp_recv_buffer": "128 KiB",
                                     "tcp_autotune": False},
                    "processes": [{"path": "udp_blast",
                                   "args": ["server=m", "port=1", "count=1"]}],
                },
            },
        }
    )
    graph = load_graph(cfg.network.graph)
    (spec,) = expand_hosts_hybrid(cfg, graph)
    t = spec.tcp_cfg
    assert t.send_buf == 64 * 1024  # cascaded default
    assert t.recv_buf == 128 * 1024  # per-host override
    assert t.nagle is True and t.autotune is False
    # unknown knobs are named loudly
    import pytest

    with pytest.raises(ConfigError, match="tcp_typo"):
        ConfigOptions.from_dict(
            {
                "general": {"stop_time": "1 s"},
                "network": {"graph": {"type": "1_gbit_switch"}},
                "host_option_defaults": {"tcp_typo": 1},
                "hosts": {"m": {"network_node_id": 0, "processes": [
                    {"model": "timer"}]}},
            }
        )


def test_syn_carries_sack_ok_on_wire():
    client, server, wire = handshake()
    client.send(b"z" * 100)
    wire.run(until=lambda: server.rcv_buf.readable() == 100)
    # the handshake helper feeds the SYN directly; check the SYN-ACK too
    synacks = [s for _, snd, s in wire.sent if snd == "b" and s.flags & SYN]
    assert all(s.sack_ok for s in synacks)
