"""shadowlint gates: every rule pack must (a) fire on a known-bad fixture
and (b) stay quiet on the real tree; heartbeat format generations must
round-trip through `parse_shadow --strict`; the jaxpr audit must hold the
lane-width and fingerprint invariants on the echo config.

Stage A tests import no JAX (that is the point of stage A); the jaxpr
audit test and the live-emitter round-trip do.
"""

from __future__ import annotations

import json
import os
import re
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.lint.astlint import Project, run_stage_a  # noqa: E402
from tools.lint import schema as lint_schema  # noqa: E402


def _mk(tmp_path, relpath: str, src: str) -> None:
    p = tmp_path / relpath
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(src))


# --------------------------------------------------------------------------
# R1: jit purity
# --------------------------------------------------------------------------


def test_r1_fires_on_clock_rng_io_and_global(tmp_path):
    _mk(tmp_path, "shadow_tpu/core/eng.py", """
        import time
        import numpy as np

        COUNTER = 0

        def helper(x):
            return np.random.rand() + time.time()

        def round_body(state):
            global COUNTER
            COUNTER += 1
            print(state)
            open("/tmp/x", "w")
            return helper(state)
    """)
    fs = run_stage_a(str(tmp_path), entries=["shadow_tpu.core.eng:round_body"])
    r1 = [f for f in fs if f.rule == "R1"]
    msgs = "\n".join(f.msg for f in r1)
    assert "time" in msgs, msgs
    assert "numpy.random" in msgs, msgs
    assert "`print`" in msgs and "`open`" in msgs, msgs
    assert "global COUNTER" in msgs, msgs
    # the banned call sits in a HELPER — reached through the call graph
    assert any("helper" in f.msg for f in r1), msgs


def test_r1_ignores_host_side_functions(tmp_path):
    _mk(tmp_path, "shadow_tpu/core/eng.py", """
        import os

        def round_body(state):
            return state + 1

        def init_state():
            return os.environ.get("SEED", "0")
    """)
    fs = run_stage_a(str(tmp_path), entries=["shadow_tpu.core.eng:round_body"])
    assert [f for f in fs if f.rule == "R1"] == []


def test_r1_control_plane_allows_io_but_not_clock(tmp_path):
    _mk(tmp_path, "shadow_tpu/core/ctl.py", """
        import time

        def controller(state, log):
            print(state, file=log)
            return time.monotonic()
    """)
    fs = run_stage_a(
        str(tmp_path),
        entries=["shadow_tpu.core.ctl:controller"],
        traced_entries=[],
    )
    r1 = [f for f in fs if f.rule == "R1"]
    msgs = "\n".join(f.msg for f in r1)
    assert "time" in msgs, msgs  # clock read: banned even host-side
    assert "print" not in msgs, msgs  # host I/O: fine in the control plane


# --------------------------------------------------------------------------
# R2: lane widths
# --------------------------------------------------------------------------


def test_r2_fires_on_narrowing_and_implicit_dtype(tmp_path):
    _mk(tmp_path, "shadow_tpu/core/eng.py", """
        import jax.numpy as jnp

        def f(ev, vals, mk):
            t32 = ev.t.astype(jnp.int32)          # narrowing a time lane
            t = jnp.asarray(vals)                 # implicit width
            q = mk(order=jnp.zeros((4,), jnp.int32))   # wrong width
            e = mk(t=5)                           # bare int literal
            ok = ev.order.astype(jnp.int64)       # widening: fine
            ok2 = mk(kind=jnp.zeros((4,), jnp.int32))  # registered i32: fine
            return t32, t, q, e, ok, ok2
    """)
    fs = run_stage_a(str(tmp_path), entries=[])
    r2 = [f for f in fs if f.rule == "R2"]
    msgs = "\n".join(f.msg for f in r2)
    assert "`t.astype(int32)` narrows" in msgs, msgs
    assert "constructed without an explicit dtype" in msgs, msgs
    assert "`order` constructed as int32" in msgs, msgs
    assert "bare int literal for 64-bit lane `t`" in msgs, msgs
    assert len(r2) == 4, msgs  # the two `ok` lines stay quiet


def test_r2_quiet_on_dtype_preserving_idioms(tmp_path):
    _mk(tmp_path, "shadow_tpu/core/eng.py", """
        import jax.numpy as jnp

        def f(ob, src):
            t = jnp.full_like(ob.t, 42)            # *_like inherits dtype
            order = jnp.asarray(src, jnp.int64)    # explicit
            occ = (ob.t != 42).astype(jnp.int32)   # bool compare: no lane
            return t, order, occ
    """)
    fs = run_stage_a(str(tmp_path), entries=[])
    assert [f for f in fs if f.rule == "R2"] == []


# --------------------------------------------------------------------------
# R4: static-arg hygiene
# --------------------------------------------------------------------------


def test_r4_fires_on_item_and_lane_int(tmp_path):
    _mk(tmp_path, "shadow_tpu/core/eng.py", """
        def round_body(st, s):
            n = int(st.now)         # traced lane -> Python int
            v = st.seq.item()       # .item() in traced scope
            k = int(getattr(s, "count_max", 1) or 1)  # static metadata: fine
            return n + v + k
    """)
    fs = run_stage_a(str(tmp_path), entries=["shadow_tpu.core.eng:round_body"])
    r4 = [f for f in fs if f.rule == "R4"]
    msgs = "\n".join(f.msg for f in r4)
    assert "int(...now...)" in msgs, msgs
    assert ".item()" in msgs, msgs
    assert len(r4) == 2, msgs


# --------------------------------------------------------------------------
# R6: timer-wheel registry lockstep
# --------------------------------------------------------------------------


def test_r6_fires_on_wheel_registry_drift(tmp_path):
    """Every failure mode of the wheel/queue width lockstep: a width
    disagreement, an unpaired wheel lane, a missing shape entry, and a
    BucketQueue field with no wheel.* registration."""
    _mk(tmp_path, "shadow_tpu/core/lanes.py", """
        STATE_LANES = {
            "queue.t": "int64",
            "queue.order": "int64",
            "wheel.t": "int32",
            "wheel.order": "int64",
            "wheel.ghost": "int64",
        }
        STATE_LANE_SHAPES = {
            "queue.t": ("H", "C"),
            "wheel.t": ("H", "WS"),
            "wheel.order": ("H", "WS"),
        }
        WHEEL_LANE_OF_QUEUE = {
            "wheel.t": "queue.t",
            "wheel.order": "queue.order",
            "wheel.ghost": "queue.nonexistent",
        }
        STATS_EXPORT_EXEMPT = {}
        HEARTBEAT_LEGACY_KEYS = frozenset()
        LANE_WIDTHS = {}
        FUNC_RETURN_LANES = {}
        BITS = {"int64": 64, "int32": 32}
        def lane_width_bits(name):
            return None
    """)
    _mk(tmp_path, "shadow_tpu/ops/events.py", """
        from typing import NamedTuple

        class BucketQueue(NamedTuple):
            t: int
            order: int
            extra_plane: int
    """)
    proj = Project(str(tmp_path), extra_dirs=())
    fs = lint_schema.check_wheel_registry(proj)
    msgs = "\n".join(f.msg for f in fs)
    assert "disagree on width" in msgs, msgs  # wheel.t int32 vs queue.t int64
    assert "`queue.nonexistent`, which is not in STATE_LANES" in msgs, msgs
    assert "wheel.ghost has no STATE_LANE_SHAPES entry" in msgs, msgs
    assert "BucketQueue.extra_plane" in msgs, msgs
    assert "`wheel.ghost` is registered but BucketQueue" in msgs, msgs


def test_r6_clean_on_repo():
    proj = Project(REPO)
    assert lint_schema.check_wheel_registry(proj) == []


# --------------------------------------------------------------------------
# R3: stats schema + trace columns
# --------------------------------------------------------------------------


def _schema_project(tmp_path, engine_src):
    _mk(tmp_path, "shadow_tpu/core/engine.py", engine_src)
    return Project(str(tmp_path), extra_dirs=())


def test_r3_fires_on_schema_drift(tmp_path):
    proj = _schema_project(tmp_path, """
        from typing import NamedTuple

        class Stats(NamedTuple):
            events: int
            mystery: int

        def _init_stats():
            return Stats(events=1)

        class Engine:
            def state_specs(self):
                return Stats(events=1, bogus_spec=2)

        def upd(st):
            return st.stats._replace(not_a_field=1)
    """)
    fs = lint_schema.check_stats_schema(proj)
    msgs = "\n".join(f.msg for f in fs)
    assert "Stats.mystery missing from _init_stats" in msgs, msgs
    assert "Stats.mystery missing from Engine.state_specs" in msgs, msgs
    assert "`bogus_spec`, which is not a Stats field" in msgs, msgs
    assert "stats._replace(not_a_field=...)" in msgs, msgs
    assert "no entry in shadow_tpu/core/lanes.py" in msgs, msgs  # stats.mystery


def test_r3_trace_columns_append_only(tmp_path):
    _mk(tmp_path, "shadow_tpu/obs/tracer.py", """
        TRACE_FIELDS = ("round", "events", "window_start")
    """)
    proj = Project(str(tmp_path), extra_dirs=())
    cols = tmp_path / "cols.txt"

    # reorder/remove -> violation
    cols.write_text("round\nwindow_start\nevents\n")
    fs = lint_schema.check_trace_columns(proj, columns_file=str(cols))
    assert fs and "APPEND-ONLY" in fs[0].msg

    # growth without registering -> violation naming the new column
    cols.write_text("round\nevents\n")
    fs = lint_schema.check_trace_columns(proj, columns_file=str(cols))
    assert fs and "window_start" in fs[0].msg

    # exact match -> clean
    cols.write_text("round\nevents\nwindow_start\n")
    assert lint_schema.check_trace_columns(proj, columns_file=str(cols)) == []


# --------------------------------------------------------------------------
# R5: heartbeat format compat
# --------------------------------------------------------------------------


def test_r5_fires_on_unparsed_field_and_dead_branch(tmp_path):
    _mk(tmp_path, "shadow_tpu/sim.py", '''
        def heartbeat_line(now, wall):
            return f"[heartbeat] sim_time={now}s zzz={wall} ratio=1.0x"
    ''')
    proj = Project(str(tmp_path), extra_dirs=())
    gens = tmp_path / "gens.txt"
    gens.write_text("[heartbeat] sim_time=1.0s zzz=2 ratio=1.0x\nbroken hb line\n")
    hb_re = re.compile(
        r"\[heartbeat\] sim_time=(?P<sim>[\d.]+)s "
        r"(?:retired=(?P<retired>\d+) )?ratio=(?P<ratio>[\d.]+)x"
    )
    fs = lint_schema.check_heartbeat_compat(
        proj, heartbeat_re=hb_re, generations_file=str(gens)
    )
    msgs = "\n".join(f.msg for f in fs)
    assert "`zzz=` is emitted" in msgs, msgs          # emitted, unparsed
    assert "matches `retired=`" in msgs, msgs         # parsed, never emitted
    assert "no longer parses" in msgs, msgs           # broken generation line


def test_r5_suffix_key_is_not_a_match(tmp_path):
    """An emitted key that is a SUFFIX of a parsed key (`hwm=` vs `q_hwm=`)
    must still be flagged — matching is against the parser's literal key
    set, never substring."""
    _mk(tmp_path, "shadow_tpu/sim.py", '''
        def heartbeat_line(now, hwm):
            return f"[heartbeat] sim_time={now}s hwm={hwm} ratio=1.0x"
    ''')
    proj = Project(str(tmp_path), extra_dirs=())
    gens = tmp_path / "gens.txt"
    gens.write_text("")
    hb_re = re.compile(
        r"\[heartbeat\] sim_time=(?P<sim>[\d.]+)s "
        r"(?:q_hwm=(?P<q_hwm>\d+) )?ratio=(?P<ratio>[\d.]+)x"
    )
    fs = lint_schema.check_heartbeat_compat(
        proj, heartbeat_re=hb_re, generations_file=str(gens)
    )
    msgs = "\n".join(f.msg for f in fs)
    assert "`hwm=` is emitted" in msgs, msgs


def test_r5_harvests_optional_field_assignments(tmp_path):
    _mk(tmp_path, "shadow_tpu/sim.py", '''
        def heartbeat_line(now, gear=None):
            gear_f = f"gear={gear} " if gear is not None else ""
            return f"[heartbeat] sim_time={now}s {gear_f}ratio=1.0x"
    ''')
    proj = Project(str(tmp_path), extra_dirs=())
    keys = lint_schema.emitted_heartbeat_keys(proj)
    assert set(keys) == {"sim_time", "gear", "ratio"}


# --------------------------------------------------------------------------
# the real tree is clean
# --------------------------------------------------------------------------


def test_stage_a_clean_on_repo():
    from tools.lint.__main__ import (
        BASELINE_FILE, check_suppression_policy, load_baseline,
        split_suppressed,
    )
    from tools.lint.schema import run_schema_rules

    project = Project(REPO)
    findings = run_stage_a(REPO, project=project)
    findings += run_schema_rules(REPO, project=project)
    suppressions = load_baseline(BASELINE_FILE)
    active, suppressed = split_suppressed(findings, suppressions)
    assert active == [], "\n".join(str(f) for f in active)
    # acceptance: zero suppressions in core/ and ops/
    assert check_suppression_policy(suppressions) == []
    for s in suppressions:
        assert not s["path"].startswith(("shadow_tpu/core/", "shadow_tpu/ops/"))


def test_cli_ast_only_fast_and_clean():
    import time as _time

    t0 = _time.monotonic()
    r = subprocess.run(
        [sys.executable, "-m", "tools.lint", "--ast-only"],
        cwd=REPO, capture_output=True, text=True, timeout=60,
    )
    wall = _time.monotonic() - t0
    assert r.returncode == 0, r.stdout + r.stderr
    assert wall < 30, f"stage A took {wall:.1f}s — tier-1 pre-stage budget is 30s"


# --------------------------------------------------------------------------
# heartbeat generations: runtime round-trip through parse_shadow --strict
# --------------------------------------------------------------------------


def _generation_lines():
    with open(os.path.join(REPO, "tools", "lint", "heartbeat_generations.txt")) as f:
        return [
            ln.rstrip("\n") for ln in f
            if ln.strip() and not ln.lstrip().startswith("#")
        ]


def test_generations_match_statically():
    from tools.parse_shadow import HEARTBEAT_RE

    for ln in _generation_lines():
        assert HEARTBEAT_RE.search(ln), f"generation line no longer parses: {ln!r}"


def _run_parse_shadow(tmp_path, log_text: str, strict: bool):
    data = tmp_path / "data"
    data.mkdir(exist_ok=True)
    log = tmp_path / "run.log"
    log.write_text(log_text)
    out = tmp_path / "out.json"
    cmd = [
        sys.executable, os.path.join(REPO, "tools", "parse_shadow.py"),
        str(data), "--log", str(log), "-o", str(out),
    ]
    if strict:
        cmd.append("--strict")
    r = subprocess.run(cmd, cwd=REPO, capture_output=True, text=True, timeout=60)
    return r, out


def test_generations_roundtrip_strict(tmp_path):
    lines = _generation_lines()
    r, out = _run_parse_shadow(tmp_path, "\n".join(lines) + "\n", strict=True)
    assert r.returncode == 0, r.stderr
    hbs = json.loads(out.read_text())["heartbeats"]
    assert len(hbs) == len(lines)
    # spot-check one field per generation era
    assert hbs[0]["windows"] == 10 and hbs[0]["sim"] == 0.5
    assert hbs[1]["rss_gib"] == 1.25
    assert any(h.get("gear") == 4 for h in hbs)
    assert any(h.get("faults_dropped") == 3 and h.get("faults_delayed") == 5 for h in hbs)
    assert any(h.get("rep_done") == 3 and h.get("rep_total") == 6 for h in hbs)


def test_strict_rejects_malformed_heartbeat(tmp_path):
    bad = "[heartbeat] sim_time=borked wall=nope\nsome other stderr line\n"
    r, _ = _run_parse_shadow(tmp_path, bad, strict=True)
    assert r.returncode == 2, (r.returncode, r.stdout, r.stderr)
    assert "unparseable heartbeat" in r.stderr
    # default mode keeps the old tolerant behavior
    r2, out = _run_parse_shadow(tmp_path, bad, strict=False)
    assert r2.returncode == 0, r2.stderr
    assert json.loads(out.read_text())["heartbeats"] == []


def test_strict_rejects_trailing_unknown_field(tmp_path):
    """A line that MATCHES the regex but carries an extra field past the
    parsed span would be silently truncated — strict mode refuses it."""
    sneaky = (
        "[heartbeat] sim_time=1.000s wall=2.50s events=99 rounds=40 "
        "ratio=0.40x newfield=7\n"
    )
    r, _ = _run_parse_shadow(tmp_path, sneaky, strict=True)
    assert r.returncode == 2, (r.returncode, r.stdout, r.stderr)
    assert "past the parsed span" in r.stderr
    r2, out = _run_parse_shadow(tmp_path, sneaky, strict=False)
    assert r2.returncode == 0  # tolerant mode: parsed, field dropped
    assert json.loads(out.read_text())["heartbeats"][0]["rounds"] == 40


def test_live_emitter_roundtrips_strict(tmp_path):
    """The CURRENT heartbeat_line output (every optional-field combination)
    strict-parses — the runtime half of R5."""
    from shadow_tpu.sim import heartbeat_line  # imports jax (x64 setup)

    lines = [
        heartbeat_line(1_000_000_000, 2.5, 100, 30, 10, 4096, 7),
        heartbeat_line(
            2_000_000_000, 2.5, 100, 30, 10, 0, 7,
            fault=(2, 3), gear=4, rep=(1, 8),
        ),
    ]
    r, out = _run_parse_shadow(tmp_path, "\n".join(lines) + "\n", strict=True)
    assert r.returncode == 0, r.stderr
    hbs = json.loads(out.read_text())["heartbeats"]
    assert len(hbs) == 2
    assert hbs[1]["gear"] == 4 and hbs[1]["rep_total"] == 8


# --------------------------------------------------------------------------
# stage B: jaxpr audit
# --------------------------------------------------------------------------


def test_jaxpr_audit_echo_clean():
    from tools.lint.jaxpr_audit import run_audit

    findings, report = run_audit(root=REPO, configs=("echo",))
    rep = report["echo"]
    if rep["fingerprint_status"] == "unrecorded":
        # foreign jax version: the only acceptable finding is the
        # demand to pin a fingerprint — lane/scatter checks still gate
        assert all("no primitive fingerprint" in str(f) for f in findings)
    else:
        assert findings == [], "\n".join(str(f) for f in findings)
        assert rep["fingerprint_status"] == "ok"
    # digest-feeding lanes are integer: no float scatter-add may appear
    assert rep["float_scatter_adds"] == 0
    assert rep["eqns"] > 100  # a real round body, not a stub trace


def test_jaxpr_fingerprint_detects_churn(tmp_path):
    import jax

    from tools.lint import jaxpr_audit

    with open(jaxpr_audit.FINGERPRINT_FILE) as f:
        recorded = json.load(f)
    ver = jax.__version__
    if ver not in recorded or "echo" not in recorded[ver]:
        pytest.skip(f"no recorded fingerprint for jax=={ver}")
    bad = json.loads(json.dumps(recorded))
    bad[ver]["echo"]["eqns"] += 1
    bad[ver]["echo"]["primitives"]["add"] = (
        bad[ver]["echo"]["primitives"].get("add", 0) + 1
    )
    fp = tmp_path / "fp.json"
    fp.write_text(json.dumps(bad))
    findings, report = jaxpr_audit.run_audit(
        root=REPO, configs=("echo",), fingerprint_file=str(fp)
    )
    assert any("fingerprint changed" in str(f) for f in findings), report
    # a mismatch must NOT silently rewrite the recorded baseline
    assert json.loads(fp.read_text()) == bad
