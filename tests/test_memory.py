"""HBM & capacity observatory (shadow_tpu/obs/memory.py, PR 9).

Gates, mirroring the ISSUE acceptance:
  - the static byte model is single-source: STATE_LANE_SHAPES covers
    STATE_LANES exactly, and every registered plane's formula bytes
    EQUAL the live carry leaf's bytes across flat/bucketed x trace x
    pressure shapes;
  - static-model totals agree with `Compiled.memory_analysis()` within
    tolerance on echo+phold CPU configs (and with jax.eval_shape avals
    exactly, via resized_avals);
  - observer exactness: digests/events/drops are bit-identical with the
    observatory sampling interleaved vs absent, across models x queue
    layouts x K x world (the observatory adds NO traced code — the
    jaxpr fingerprint gate in tools/lint pins the stronger program-level
    claim);
  - the pressure plane refuses a predicted-OOM rung BEFORE dispatch
    (fake memory_stats), and admits growth when headroom suffices;
  - tools/hbm_report.py CLI smoke (+ --check), subprocess-isolated per
    the documented jaxlib-0.4.37 corruption posture;
  - heartbeat `hbm=` round-trips through parse_shadow --strict.

Engine-harness legs run in-process (the stable path on this box);
compiled-Simulation legs go through tests/subproc.py."""

from __future__ import annotations

import dataclasses
import json
import os
import subprocess
import sys

import jax
import numpy as np
import pytest

from shadow_tpu.config.options import ConfigError, PressureOptions
from shadow_tpu.core import Engine
from shadow_tpu.core import lanes
from shadow_tpu.core.pressure import PressureAbort, ResilienceController
from shadow_tpu.obs import memory as M
from tests.engine_harness import build_sim, mk_hosts

MS = 1_000_000
_REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def _build(model, hosts, stop, pressure_abort=False, **kw):
    cfg, m, params, mstate, events = build_sim(
        model, hosts, stop, rounds_per_chunk=16, **kw
    )
    if pressure_abort:
        cfg = dataclasses.replace(cfg, pressure_abort=True)
    mesh = None
    if cfg.world > 1:
        mesh = jax.sharding.Mesh(
            np.array(jax.devices()[: cfg.world]), ("hosts",)
        )
    eng = Engine(cfg, m, mesh)
    state, params = eng.init_state(params, mstate, events, seed=1)
    return cfg, eng, state, params


def _leaf_at(state, path):
    obj = state
    for part in path.split("."):
        obj = getattr(obj, part)
    return obj


# ---------------------------------------------------------------------------
# static model: single-source registry coverage + formula == carry bytes
# ---------------------------------------------------------------------------


def test_shape_registry_covers_state_lanes_exactly():
    assert set(lanes.STATE_LANE_SHAPES) == set(lanes.STATE_LANES), (
        "STATE_LANE_SHAPES and STATE_LANES must cover the same paths — "
        "the byte model has exactly one source to drift from"
    )


@pytest.mark.parametrize(
    "queue_block,trace,pressure",
    [(0, 0, False), (8, 16, False), (0, 16, True), (8, 0, True)],
    ids=["flat", "bucketed+trace", "flat+trace+pressure", "bucketed+pressure"],
)
def test_formula_bytes_equal_carry_leaves(queue_block, trace, pressure):
    """Every registered plane's formula bytes == the live carry leaf's
    bytes (exact, not tolerance): the strong single-source gate."""
    cfg, eng, state, params = _build(
        "phold", mk_hosts(8, {"mean_delay": "20 ms", "population": 2}),
        200_000_000, qcap=32, queue_block=queue_block,
        trace_rounds=trace, pressure_abort=pressure,
    )
    dims = M.dims_of_config(cfg)
    comps = M.registered_component_bytes(dims)
    seen = set()
    for comp, paths in comps.items():
        for path, want in paths.items():
            leaf = _leaf_at(state, path)
            assert M.leaf_nbytes(leaf) == want, (
                f"{path}: formula {want} != leaf {M.leaf_nbytes(leaf)} "
                f"({leaf.shape} {leaf.dtype})"
            )
            seen.add(path)
    # absent-plane logic: bucket caches only on bucketed queues, trace
    # ring only when tracing, stats.pressure only under escalate/abort
    assert ("queue.bt" in seen) == bool(queue_block)
    assert ("trace.rows" in seen) == bool(trace)
    assert ("stats.pressure" in seen) == pressure


def test_static_model_totals_and_per_host():
    cfg, eng, state, params = _build(
        "phold", mk_hosts(8, {"mean_delay": "20 ms", "population": 2}),
        200_000_000, qcap=16,
    )
    sm = M.static_model(cfg, state, params)
    # measured state total (metadata walk) must equal registered formula
    # total + the unregistered planes it reports
    assert sm["state_bytes"] == sm["registered_bytes"] + sum(
        sm["unregistered"].values()
    )
    assert sm["state_bytes_measured"] == sm["state_bytes"]
    assert sm["total_bytes"] == sm["state_bytes"] + sm["params_bytes"]
    assert sm["per_host_bytes"] * cfg.num_hosts <= sm["total_bytes"]
    # replica scaling multiplies state, not params
    sm4 = M.static_model(cfg, state, params, replicas=4)
    assert sm4["state_bytes"] == 4 * sm["state_bytes"]
    assert sm4["params_bytes"] == sm["params_bytes"]


def test_static_model_follows_grown_state():
    """After an escalation regrow the model prices the state's ACTUAL
    shapes (dims_of_state), not the config's base — measured and
    formula totals stay equal."""
    from shadow_tpu.ops.events import migrate_queue

    cfg, eng, state, params = _build(
        "phold", mk_hosts(8, {"mean_delay": "20 ms", "population": 2}),
        200_000_000, qcap=16,
    )
    grown = state._replace(
        queue=migrate_queue(state.queue, 32, cfg.queue_block)
    )
    sm = M.static_model(cfg, grown, params)
    assert sm["state_bytes"] == sm["state_bytes_measured"]
    dims32 = M.dims_of(
        hosts_per_shard=cfg.hosts_per_shard, queue_capacity=32,
        send_budget=cfg.sends_per_host_round,
    )
    assert sm["components"]["queue"] == sum(
        M.registered_component_bytes(dims32)["queue"].values()
    )


def test_state_bytes_at_scales_with_shape():
    cfg, *_ = _build(
        "phold", mk_hosts(8, {"mean_delay": "20 ms", "population": 2}),
        200_000_000, qcap=16,
    )
    base = M.state_bytes_at(cfg, 16, cfg.sends_per_host_round)
    grown_q = M.state_bytes_at(cfg, 32, cfg.sends_per_host_round)
    grown_b = M.state_bytes_at(cfg, 16, 2 * cfg.sends_per_host_round)
    assert grown_q > base and grown_b > base
    # queue growth delta is exactly the queue planes' delta
    dims16 = M.dims_of(hosts_per_shard=cfg.hosts_per_shard,
                       queue_capacity=16, send_budget=cfg.sends_per_host_round)
    dims32 = M.dims_of(hosts_per_shard=cfg.hosts_per_shard,
                       queue_capacity=32, send_budget=cfg.sends_per_host_round)
    dq = (
        sum(M.registered_component_bytes(dims32)["queue"].values())
        - sum(M.registered_component_bytes(dims16)["queue"].values())
    )
    assert grown_q - base == dq


# ---------------------------------------------------------------------------
# compiled ledger: memory_analysis + eval_shape agreement
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("case", ["echo", "phold"])
def test_static_model_vs_memory_analysis(case):
    """ISSUE acceptance: the static-model total agrees with
    `Compiled.memory_analysis()` argument bytes within the documented
    tolerance (10% — XLA pads/aligns, the model counts raw lanes) on
    echo+phold CPU configs."""
    if case == "echo":
        hosts = (
            [dict(host_id=0, name="server", start_time=0,
                  model_args={"role": "server"})]
            + [dict(host_id=i, name=f"c{i}", start_time=0,
                    model_args={"role": "client", "peer": "server",
                                "interval": "20 ms", "size_bytes": 256})
               for i in range(1, 5)]
        )
        cfg, eng, state, params = _build("udp_echo", hosts, 200_000_000,
                                         qcap=16)
    else:
        cfg, eng, state, params = _build(
            "phold", mk_hosts(8, {"mean_delay": "20 ms", "population": 2}),
            200_000_000, qcap=16,
        )
    led = M.compiled_ledger(eng, state, params)
    base = led["base"]
    assert "argument_bytes" in base, base
    sm = M.static_model(cfg, state, params)
    rel = abs(sm["total_bytes"] - base["argument_bytes"]) / base[
        "argument_bytes"
    ]
    assert rel < 0.10, (sm["total_bytes"], base)
    # peak decomposition present and sane
    assert base["peak_bytes"] >= base["temp_bytes"]


def test_resized_avals_match_formula_delta():
    """`resized_avals` (jax.eval_shape through the real migration ops)
    re-seats the state at a grown shape whose registered-plane bytes
    match the formula at that shape exactly."""
    cfg, eng, state, params = _build(
        "phold", mk_hosts(8, {"mean_delay": "20 ms", "population": 2}),
        200_000_000, qcap=16,
    )
    avals = M.resized_avals(state, 32, 16, cfg.queue_block)
    dims = M.dims_of(
        hosts_per_shard=cfg.hosts_per_shard, queue_capacity=32,
        send_budget=16, queue_block=cfg.queue_block,
        trace_rounds=cfg.trace_rounds, pressure=cfg.pressure_abort,
    )
    comps = M.registered_component_bytes(dims)
    for path, want in {**comps["queue"], **comps["outbox"]}.items():
        assert M.leaf_nbytes(_leaf_at(avals, path)) == want, path


def test_ledger_covers_cached_rungs():
    """After run_chunk_resized compiled a rung, the ledger reports it
    (lowered at ITS shape) alongside the base program."""
    cfg, eng, state, params = _build(
        "phold", mk_hosts(8, {"mean_delay": "20 ms", "population": 2}),
        200_000_000, qcap=16,
    )
    from shadow_tpu.core.checkpoint import snapshot_state
    from shadow_tpu.ops.events import migrate_queue

    grown = snapshot_state(state)._replace(
        queue=migrate_queue(state.queue, 32, cfg.queue_block)
    )
    out = eng.run_chunk_resized(grown, params, 0, 32, cfg.sends_per_host_round)
    jax.block_until_ready(out)
    led = M.compiled_ledger(eng, state, params)
    keys = set(led)
    assert "base" in keys
    rung = [k for k in keys if k.startswith("cap=32/")]
    assert rung, keys
    assert "argument_bytes" in led[rung[0]]
    # the grown rung's arguments are strictly bigger than the base's
    assert led[rung[0]]["argument_bytes"] > led["base"]["argument_bytes"]


# ---------------------------------------------------------------------------
# observer exactness: sampling cannot move a digest
# ---------------------------------------------------------------------------

_ECHO_HOSTS = (
    [dict(host_id=0, name="server", start_time=0,
          model_args={"role": "server"})]
    + [dict(host_id=i, name=f"c{i}", start_time=0,
            model_args={"role": "client", "peer": "server",
                        "interval": "4 ms", "size_bytes": 2000})
       for i in range(1, 5)]
)

_OBS_CASES = {
    # pairwise coverage of model x layout x K (the observatory is
    # host-side only, so the property is structural; world=8 below)
    "echo-flat-k1": ("udp_echo", _ECHO_HOSTS, 200_000_000,
                     dict(bw_bits=2_000_000, loss=0.05)),
    "echo-bucketed-k4": ("udp_echo", _ECHO_HOSTS, 200_000_000,
                         dict(bw_bits=2_000_000, loss=0.05,
                              queue_block=8, microstep_events=4)),
    "phold-bucketed-k1": ("phold",
                          mk_hosts(8, {"mean_delay": "20 ms",
                                       "population": 3}),
                          300_000_000, dict(loss=0.1, queue_block=8)),
    "phold-flat-k4": ("phold",
                      mk_hosts(8, {"mean_delay": "20 ms", "population": 3}),
                      300_000_000, dict(loss=0.1, microstep_events=4)),
    "tgen-flat-k1": ("tgen_tcp",
                     mk_hosts(5, {"flow_segs": 8, "flows": 1, "cwnd_cap": 8,
                                  "rto_min": "100 ms"}),
                     1_500_000_000,
                     dict(loss=0.05, latency=10_000_000, sends_budget=16)),
    "tgen-bucketed-k4": ("tgen_tcp",
                         mk_hosts(5, {"flow_segs": 8, "flows": 1,
                                      "cwnd_cap": 8, "rto_min": "100 ms"}),
                         1_500_000_000,
                         dict(loss=0.05, latency=10_000_000,
                              sends_budget=16, queue_block=8,
                              microstep_events=4)),
}


def _run_engine(model, hosts, stop, monitor=None, world=1, **kw):
    cfg, eng, state, params = _build(model, hosts, stop, world=world, **kw)
    chunks = 0
    while not bool(np.asarray(jax.device_get(state.done)).all()):
        state = eng.run_chunk(state, params)
        if monitor is not None:
            # the full observatory surface between chunks: live sample
            # (modeled fallback), static model, shape predictor
            jax.block_until_ready(state)
            monitor.sample(modeled_bytes=(
                M.tree_bytes(state) + M.tree_bytes(params)
            ) // cfg.world)
            M.static_model(cfg, state, params)
            M.state_bytes_at(cfg, 2 * cfg.queue_capacity,
                             cfg.sends_per_host_round)
        chunks += 1
        assert chunks < 500
    s = jax.device_get(state.stats)
    drops = (
        int(np.asarray(jax.device_get(state.queue.dropped)).sum()),
        int(np.asarray(s.pkts_budget_dropped).sum()),
        int(np.asarray(s.pkts_lost).sum()),
        int(np.asarray(s.ob_dropped).sum()),
        int(np.asarray(s.a2a_shed).sum()),
    )
    return (
        np.asarray(s.digest).copy(),
        int(np.asarray(s.events).sum()),
        drops,
        monitor,
    )


@pytest.mark.parametrize("case", sorted(_OBS_CASES), ids=sorted(_OBS_CASES))
def test_observer_exactness(case):
    model, hosts, stop, kw = _OBS_CASES[case]
    d0, ev0, drops0, _ = _run_engine(model, hosts, stop, monitor=None, **kw)
    mon = M.MemoryMonitor([jax.devices()[0]])
    d1, ev1, drops1, mon = _run_engine(
        model, hosts, stop, monitor=mon, **kw
    )
    np.testing.assert_array_equal(d0, d1)
    assert ev0 == ev1 and drops0 == drops1
    assert mon.count > 0 and mon.hwm_bytes() > 0
    assert mon.source == "modeled"  # CPU backend has no allocator stats


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
def test_observer_exactness_world8():
    hosts = mk_hosts(16, {"mean_delay": "20 ms", "population": 3})
    kw = dict(loss=0.1, queue_block=8, microstep_events=4)
    d0, ev0, drops0, _ = _run_engine(
        "phold", hosts, 300_000_000, monitor=None, world=8, **kw
    )
    mon = M.MemoryMonitor(list(jax.devices()[:8]))
    d1, ev1, drops1, mon = _run_engine(
        "phold", hosts, 300_000_000, monitor=mon, world=8, **kw
    )
    np.testing.assert_array_equal(d0, d1)
    assert ev0 == ev1 and drops0 == drops1
    assert len(mon.peak) == 8 and all(p > 0 for p in mon.peak)


# ---------------------------------------------------------------------------
# live monitor + guard units (fake memory_stats)
# ---------------------------------------------------------------------------


def _fake_stats(used, limit):
    return lambda d: {
        "bytes_in_use": used, "peak_bytes_in_use": used,
        "bytes_limit": limit,
    }


def test_monitor_device_source_and_headroom():
    mon = M.MemoryMonitor(
        devices=[object()], stats_fn=_fake_stats(600, 1000)
    )
    mon.sample()
    assert mon.source == "device"
    assert mon.headroom_bytes() == 400
    assert mon.hwm_bytes() == 600
    rep = mon.report()
    assert rep["limit_bytes"] == 1000 and rep["headroom_bytes"] == 400


def test_monitor_modeled_fallback():
    mon = M.MemoryMonitor(devices=[object()], stats_fn=lambda d: None)
    mon.sample(modeled_bytes=1234)
    assert mon.source == "modeled"
    assert mon.headroom_bytes() is None  # no limit -> guard inert
    assert mon.hwm_bytes() == 1234


def test_guard_admit_math():
    cfg, *_ = _build(
        "phold", mk_hosts(8, {"mean_delay": "20 ms", "population": 2}),
        200_000_000, qcap=16,
    )
    need = M.MemoryGuard(cfg, None).predicted_need_bytes(16, 8, 32, 8)
    delta = M.state_bytes_at(cfg, 32, 8) - M.state_bytes_at(cfg, 16, 8)
    assert need == int(delta * 2 * 1.25)
    # no monitor / no limit: admit everything
    ok, _, headroom = M.MemoryGuard(cfg, None).admit(16, 8, 32, 8)
    assert ok and headroom is None
    # tight measured headroom: refuse
    mon = M.MemoryMonitor([object()], stats_fn=_fake_stats(990, 1000))
    mon.sample()
    ok, need2, headroom = M.MemoryGuard(cfg, mon).admit(16, 8, 32, 8)
    assert not ok and headroom == 10 and need2 == need
    # roomy headroom: admit
    mon2 = M.MemoryMonitor([object()], stats_fn=_fake_stats(0, 1 << 40))
    mon2.sample()
    ok, *_ = M.MemoryGuard(cfg, mon2).admit(16, 8, 32, 8)
    assert ok


# ---------------------------------------------------------------------------
# pressure plane: pre-dispatch rung refusal
# ---------------------------------------------------------------------------

_PRESSURED = (
    "phold",
    mk_hosts(8, {"mean_delay": "20 ms", "population": 3}),
    300_000_000,
    dict(loss=0.1, qcap=4),
)


def _pressured_build():
    model, hosts, stop, kw = _PRESSURED
    return _build(model, hosts, stop, pressure_abort=True, **kw)


def test_controller_refuses_predicted_oom_rung_before_dispatch():
    """ISSUE acceptance: a candidate rung whose predicted footprint
    exceeds measured headroom x safety is refused/poisoned BEFORE
    dispatch — no grown program is ever compiled or dispatched."""
    cfg, eng, state, params = _pressured_build()
    mon = M.MemoryMonitor([object()], stats_fn=_fake_stats(999, 1000))
    mon.sample()
    rc = ResilienceController(
        pressure=PressureOptions(policy="escalate", max_capacity=64),
        memory=M.MemoryGuard(cfg, mon),
    )
    dispatched_shapes = []

    def dispatch(s, g, c, b):
        dispatched_shapes.append((c, b))
        return eng.run_chunk_resized(s, params, g, c, b)

    with pytest.raises(PressureAbort, match="memory guard refused"):
        while not bool(state.done):
            state, _, _ = rc.run_chunk(state, dispatch)
    assert rc.memory_refusals >= 1
    # nothing beyond the base shape was ever dispatched
    base = (cfg.queue_capacity, cfg.sends_per_host_round)
    assert set(dispatched_shapes) == {base}, dispatched_shapes
    rep = rc.report()
    assert rep["memory_refusals"] >= 1
    assert rep["headroom_bytes"] == 1
    assert rep["capacity_poisoned"]
    assert rc.abort_export_state() is not None


def test_controller_admits_growth_with_headroom():
    """With roomy measured headroom the guard is admission-only: the
    escalation proceeds, the run finishes drop-free, and the accepted
    digests match the unguarded escalate run bit-for-bit."""
    cfg, eng, state, params = _pressured_build()

    def run(with_guard):
        cfg2, eng2, st, pr = _pressured_build()
        mem = None
        if with_guard:
            mon = M.MemoryMonitor(
                [object()], stats_fn=_fake_stats(0, 1 << 40)
            )
            mon.sample()
            mem = M.MemoryGuard(cfg2, mon)
        rc = ResilienceController(
            pressure=PressureOptions(policy="escalate", max_capacity=64),
            memory=mem,
        )
        while not bool(st.done):
            st, _, _ = rc.run_chunk(
                st, lambda s, g, c, b: eng2.run_chunk_resized(s, pr, g, c, b)
            )
        return st, rc

    st_g, rc_g = run(True)
    st_p, rc_p = run(False)
    assert rc_g.regrows + rc_g.proactive_regrows > 0
    assert rc_g.memory_refusals == 0
    assert int(np.asarray(jax.device_get(st_g.queue.dropped)).sum()) == 0
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(st_g.stats.digest)),
        np.asarray(jax.device_get(st_p.stats.digest)),
    )


def test_proactive_refusal_skips_quietly():
    """A refused PROACTIVE regrow (nothing dropped yet) skips the
    boundary migration and the run continues at the current shape."""
    model, hosts, stop, _ = _PRESSURED
    cfg, eng, state, params = _build(
        model, hosts, stop, pressure_abort=True, loss=0.1, qcap=16,
    )
    mon = M.MemoryMonitor([object()], stats_fn=_fake_stats(999, 1000))
    mon.sample()
    rc = ResilienceController(
        # headroom 0.01: any nonzero occupancy triggers a proactive
        # grow attempt at every boundary — each must be refused
        pressure=PressureOptions(policy="escalate", max_capacity=64,
                                 headroom=0.01),
        memory=M.MemoryGuard(cfg, mon),
    )
    while not bool(state.done):
        state, _, _ = rc.run_chunk(
            state, lambda s, g, c, b: eng.run_chunk_resized(s, params, g, c, b)
        )
    assert rc.memory_refusals >= 1
    assert rc.proactive_regrows == 0
    assert not rc.aborted
    assert state.queue.t.shape[1] == 16  # never grew


def test_proactive_admission_trims_to_single_axis():
    """When the COMBINED proactive growth exceeds headroom but one axis
    alone fits, the affordable single-axis migration still happens
    (review finding: skipping both wasted the cheap boundary regrow)."""
    cfg, *_ = _build(
        "phold", mk_hosts(8, {"mean_delay": "20 ms", "population": 2}),
        200_000_000, qcap=16,
    )
    base_cap, base_box = 16, cfg.sends_per_host_round
    probe = M.MemoryGuard(cfg, None)
    need_q = probe.predicted_need_bytes(base_cap, base_box, 32, base_box)
    need_both = probe.predicted_need_bytes(base_cap, base_box, 32,
                                           2 * base_box)
    assert need_q < need_both
    # headroom fits the queue-only growth, not the combined one
    mon = M.MemoryMonitor(
        [object()], stats_fn=_fake_stats(0, need_q + (need_both - need_q) // 2)
    )
    mon.sample()
    rc = ResilienceController(
        pressure=PressureOptions(policy="escalate"),
        memory=M.MemoryGuard(cfg, mon),
    )
    got = rc._admitted_proactive(base_cap, base_box, 32, 2 * base_box)
    assert got == (32, base_box)
    assert rc.memory_refusals == 1
    # nothing fits: skip entirely, never abort
    mon2 = M.MemoryMonitor([object()], stats_fn=_fake_stats(0, 1))
    mon2.sample()
    rc2 = ResilienceController(
        pressure=PressureOptions(policy="escalate"),
        memory=M.MemoryGuard(cfg, mon2),
    )
    assert rc2._admitted_proactive(base_cap, base_box, 32, 2 * base_box) \
        == (base_cap, base_box)
    assert rc2.memory_refusals == 1 and not rc2.aborted


def test_supervisor_failure_memory_uses_modeled_fallback():
    """On backends with no allocator stats the failure-time sample must
    carry the MODELED bytes, not zeros (review finding)."""
    from shadow_tpu.core.supervisor import ChunkSupervisor

    cfg, eng, state, params = _build(
        "phold", mk_hosts(8, {"mean_delay": "20 ms", "population": 2}),
        200_000_000, qcap=16,
    )
    mon = M.MemoryMonitor([object()], stats_fn=lambda d: None)
    sup = ChunkSupervisor(
        snapshot_every_chunks=1, max_retries=2, backoff_base_s=0.0,
        memory=mon,
        memory_modeled_fn=lambda: M.modeled_shard_bytes(state, params),
    )
    sup.note_state(state)
    calls = {"n": 0}

    def dispatch(st):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("transient dispatch failure")
        return eng.run_chunk(st, params)

    sup.run_chunk(state, dispatch)
    fm = sup.report()["failure_memory"]
    assert fm["bytes_in_use"] == [M.modeled_shard_bytes(state, params)]
    assert fm["bytes_in_use"][0] > 0


def test_supervisor_records_failure_memory():
    from shadow_tpu.core.supervisor import ChunkSupervisor

    cfg, eng, state, params = _build(
        "phold", mk_hosts(8, {"mean_delay": "20 ms", "population": 2}),
        200_000_000, qcap=16,
    )
    mon = M.MemoryMonitor([object()], stats_fn=_fake_stats(700, 1000))
    sup = ChunkSupervisor(
        snapshot_every_chunks=1, max_retries=2, backoff_base_s=0.0,
        memory=mon,
    )
    sup.note_state(state)
    calls = {"n": 0}

    def dispatch(st):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("transient dispatch failure")
        return eng.run_chunk(st, params)

    out = sup.run_chunk(state, dispatch)
    assert int(np.asarray(jax.device_get(out.stats.rounds))) > 0
    rep = sup.report()
    assert rep["retries"] == 1
    assert rep["failure_memory"]["bytes_in_use"] == [700]
    assert rep["failure_memory"]["headroom_bytes"] == 300


# ---------------------------------------------------------------------------
# tracer exports: wall-clock memory track + Prometheus gauges
# ---------------------------------------------------------------------------


def test_tracer_memory_track_and_gauges():
    from shadow_tpu.obs.tracer import RoundTracer

    tr = RoundTracer(8)
    tr.note_memory(100.0, [1000, 2000])
    tr.note_memory(101.0, [1500, 1800])
    chrome = tr.to_chrome_trace()
    counters = [e for e in chrome["traceEvents"]
                if e.get("ph") == "C" and e["name"] == "hbm_bytes"]
    assert len(counters) == 2
    assert counters[0]["args"] == {"shard0": 1000, "shard1": 2000}
    assert counters[1]["ts"] > counters[0]["ts"]
    text = tr.to_metrics_text()
    assert "shadow_tpu_hbm_peak_bytes 2000" in text
    assert 'shadow_tpu_shard_hbm_bytes_in_use{shard="1"} 1800' in text
    # without samples, no memory metrics appear (schema unchanged)
    assert "hbm" not in RoundTracer(8).to_metrics_text()


# ---------------------------------------------------------------------------
# campaign byte guard
# ---------------------------------------------------------------------------


def _campaign_dict():
    return {
        "general": {"stop_time": "2 s", "seed": 1},
        "network": {"graph": {"type": "1_gbit_switch"}},
        "experimental": {"event_queue_capacity": 8, "rounds_per_chunk": 8},
        "campaign": {"seeds": [1, 2], "ledger_file": None},
        "hosts": {"n": {"count": 4, "network_node_id": 0,
                  "processes": [{"model": "phold",
                                 "model_args": {"population": 2,
                                                "mean_delay": "100 ms"}}]}},
    }


def test_campaign_replica_byte_guard():
    from tools.campaign import build_campaign

    c = build_campaign(_campaign_dict(), capacity_bytes=1 << 40)
    assert c.per_replica_bytes > 0
    # R x per-replica state + nonzero shared params
    assert c.predicted_bytes > 2 * c.per_replica_bytes
    with pytest.raises(ConfigError, match="predicted"):
        build_campaign(_campaign_dict(),
                       capacity_bytes=c.per_replica_bytes)


# ---------------------------------------------------------------------------
# heartbeat hbm= round-trip
# ---------------------------------------------------------------------------


def test_heartbeat_hbm_strict_roundtrip(tmp_path):
    from shadow_tpu.sim import heartbeat_line
    from tools.parse_shadow import parse_heartbeats

    lines = [
        heartbeat_line(2_000_000_000, 3.0, 99, 80, 40, 4096, 7,
                       hbm=1 << 20),
        heartbeat_line(2_000_000_000, 3.0, 99, 80, 40, 4096, 7,
                       gear=4, cap=32, hbm=12345, rep=(1, 2)),
        heartbeat_line(2_000_000_000, 3.0, 99, 80, 40, 4096, 7),
    ]
    p = tmp_path / "hb.log"
    p.write_text("\n".join(lines) + "\n")
    parsed = parse_heartbeats(str(p), strict=True)
    assert parsed[0]["hbm"] == 1 << 20
    assert parsed[1]["hbm"] == 12345 and parsed[1]["cap"] == 32
    assert "hbm" not in parsed[2]


# ---------------------------------------------------------------------------
# subprocess legs: Simulation on/off exactness + hbm_report CLI
# ---------------------------------------------------------------------------


_SIM_WORKER = '''
import io, json, os, sys
import numpy as np
from shadow_tpu.config.options import ConfigOptions
from shadow_tpu.sim import Simulation

mem = sys.argv[1] == "on"
tmp = sys.argv[2]
cfg = ConfigOptions.from_dict({
    "general": {"stop_time": "3 s", "seed": 1,
                "heartbeat_interval": "1 s",
                "data_directory": tmp},
    "network": {"graph": {"type": "1_gbit_switch"}},
    "experimental": {"event_queue_capacity": 16,
                     "rounds_per_chunk": 8},
    "observability": {"trace": True, "memory": mem},
    "hosts": {"n": {"count": 16, "network_node_id": 0,
              "processes": [{"model": "phold",
                             "model_args": {"population": 2,
                                            "mean_delay": "100 ms"}}]}},
})
log = io.StringIO()
sim = Simulation(cfg, world=1)
r = sim.run(progress=False, log=log)
sim.write_outputs(report=r)
hb = [l for l in log.getvalue().splitlines() if "[heartbeat]" in l]
out = {
    "digest": r["determinism_digest"],
    "events": r["events_processed"],
    "drops": [r["queue_overflow_dropped"],
              r["packets_budget_dropped"], r["packets_lost"]],
    "heartbeat": hb[0] if hb else "",
    "has_memory": "memory" in r,
}
if mem:
    trace = json.load(open(os.path.join(tmp, "trace.json")))
    out["mem_track"] = len([e for e in trace["traceEvents"]
                            if e.get("ph") == "C"
                            and e.get("name") == "hbm_bytes"])
    prom = open(os.path.join(tmp, "metrics.prom")).read()
    out["prom_has_hbm"] = "shadow_tpu_hbm_peak_bytes" in prom
    m = r["memory"]
    out.update(source=m["source"], samples=m["samples"],
               hwm=m["per_shard_hwm_bytes"],
               ledger_base=m["ledger"]["base"],
               model_total=m["model"]["total_bytes"])
print(json.dumps(out))
'''


def test_simulation_memory_on_off_bit_identical(tmp_path):
    """Full-driver leg: observability.memory on vs off — digests, event
    counts, and drop counters bit-identical; the on-run's artifacts
    carry the memory{} block, hbm= heartbeats, the Chrome-trace memory
    track, and Prometheus gauges. One Simulation per subprocess
    (compiled Simulation runs are this box's corruption magnet, and
    two in one process is its worst shape — tests/subproc.py)."""
    from tests.subproc import run_isolated_json

    on = run_isolated_json(
        _SIM_WORKER, "on", str(tmp_path / "mem_on"), timeout=420
    )
    off = run_isolated_json(
        _SIM_WORKER, "off", str(tmp_path / "mem_off"), timeout=420
    )
    assert on["digest"] == off["digest"]
    assert on["events"] == off["events"]
    assert on["drops"] == off["drops"]
    assert on["source"] == "modeled" and on["samples"] > 0
    assert all(b > 0 for b in on["hwm"])
    assert "argument_bytes" in on["ledger_base"]
    assert on["model_total"] > 0
    assert "hbm=" in on["heartbeat"]
    assert "hbm=" not in off["heartbeat"]
    assert on["mem_track"] > 0
    assert on["prom_has_hbm"]
    assert not off["has_memory"]
    # strict-parse the live heartbeat through the format gate
    from tools.parse_shadow import HEARTBEAT_RE

    m = HEARTBEAT_RE.search(on["heartbeat"])
    assert m and int(m.group("hbm")) == max(on["hwm"])


def test_hybrid_memory_observatory():
    """The cosim driver's observatory leg: a hybrid (program-host) run
    with observability.memory on carries the memory{} block, hbm= in the
    windows-form heartbeat, and a digest identical to the memory-off
    run. Subprocess-isolated like every compiled-Simulation leg."""
    from tests.subproc import run_isolated_json

    worker = '''
import io, json, sys
from shadow_tpu.config.options import ConfigOptions
from shadow_tpu.cosim import HybridSimulation

mem = sys.argv[1] == "on"
cfg = ConfigOptions.from_dict({
    "general": {"stop_time": "2 s", "seed": 7,
                "heartbeat_interval": "500 ms"},
    "network": {"graph": {"type": "1_gbit_switch"}},
    "observability": {"memory": mem, "memory_ledger": False},
    "hosts": {
        "server": {"network_node_id": 0,
                   "processes": [{"path": "udp_echo_server",
                                  "args": ["port=9000"]}]},
        "client": {"network_node_id": 0,
                   "processes": [{"path": "udp_ping",
                                  "args": ["server=server", "port=9000",
                                           "count=3"],
                                  "expected_final_state": {"exited": 0}}]},
    },
})
log = io.StringIO()
sim = HybridSimulation(cfg)
r = sim.run(log=log)
hb = [l for l in log.getvalue().splitlines() if "[heartbeat]" in l]
print(json.dumps({
    "digest": r["determinism_digest"],
    "delivered": r["packets_delivered"],
    "failures": r["process_failures"],
    "heartbeat": hb[0] if hb else "",
    "memory": r.get("memory"),
}))
'''
    on = run_isolated_json(worker, "on", timeout=420)
    off = run_isolated_json(worker, "off", timeout=420)
    assert on["failures"] == 0 and off["failures"] == 0
    assert on["digest"] == off["digest"]
    assert on["delivered"] == off["delivered"]
    m = on["memory"]
    assert m is not None and off["memory"] is None
    assert m["source"] == "modeled" and m["samples"] > 0
    assert max(m["per_shard_hwm_bytes"]) > 0
    assert m["model"]["total_bytes"] > 0
    assert "ledger" not in m  # memory_ledger: false skips recompiles
    if on["heartbeat"]:  # windows-form heartbeat carries hbm=
        assert "hbm=" in on["heartbeat"]
        from tools.parse_shadow import HEARTBEAT_RE

        assert HEARTBEAT_RE.search(on["heartbeat"])


def _skip_on_corruption(proc, what):
    from tests.subproc import HEAP_CORRUPTION_RCS

    if proc.returncode in HEAP_CORRUPTION_RCS and not proc.stdout.strip():
        pytest.skip(
            f"{what}: known jaxlib corruption signature "
            f"rc={proc.returncode} (CHANGES.md env notes)"
        )


def test_hbm_report_cli_smoke(tmp_path):
    """`tools/hbm_report.py --json` on a tiny config: per-component
    breakdown + a positive max-hosts figure; then `--check` (which
    self-classifies the corruption signature) must exit 0."""
    cfg_yaml = tmp_path / "tiny.yaml"
    cfg_yaml.write_text("""
general: {stop_time: 2 s, seed: 1}
network: {graph: {type: 1_gbit_switch}}
experimental: {event_queue_capacity: 16, rounds_per_chunk: 8}
hosts:
  n:
    count: 8
    network_node_id: 0
    processes:
      - model: phold
        model_args: {population: 2, mean_delay: 100 ms}
""")
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    proc = subprocess.run(
        [sys.executable, "tools/hbm_report.py", str(cfg_yaml), "--json"],
        capture_output=True, text=True, timeout=420, env=env, cwd=_REPO,
    )
    _skip_on_corruption(proc, "hbm_report --json")
    assert proc.returncode == 0, proc.stderr[-800:]
    blob = json.loads(proc.stdout)
    assert blob["model"]["components"]["queue"] > 0
    assert blob["ledger"]["base"]["argument_bytes"] > 0
    assert blob["plan"]["max_hosts_per_device"] > 0
    assert blob["planner"]["per_host_bytes"] > 0

    proc = subprocess.run(
        [sys.executable, "tools/hbm_report.py", str(cfg_yaml), "--check"],
        capture_output=True, text=True, timeout=640, env=env, cwd=_REPO,
    )
    assert proc.returncode == 0, (proc.stdout, proc.stderr[-800:])
    assert "ok" in proc.stdout or "SKIP" in proc.stdout


# ---------------------------------------------------------------------------
# bench_compare unit
# ---------------------------------------------------------------------------


def test_bench_compare_flags_regressions(tmp_path):
    from tools.bench_compare import main as bc_main

    old = {"parsed": {"metric": "m1", "value": 10.0,
                      "hbm": {"per_shard_hwm_bytes": [1000]}}}
    new_ok = {"parsed": {"metric": "m1", "value": 9.5,
                         "hbm": {"per_shard_hwm_bytes": [1040]}}}
    new_bad = {"parsed": {"metric": "m1", "value": 8.0,
                          "hbm": {"per_shard_hwm_bytes": [2000]}}}
    p_old = tmp_path / "old.json"
    p_ok = tmp_path / "ok.json"
    p_bad = tmp_path / "bad.json"
    p_old.write_text(json.dumps(old))
    p_ok.write_text(json.dumps(new_ok))
    p_bad.write_text(json.dumps(new_bad))
    assert bc_main([str(p_old), str(p_ok)]) == 0
    assert bc_main([str(p_old), str(p_bad)]) == 1
    # a tracked metric disappearing is a regression
    p_empty = tmp_path / "empty.json"
    p_empty.write_text(json.dumps({"parsed": {"metric": "m2", "value": 1}}))
    assert bc_main([str(p_old), str(p_empty)]) == 1


# ---------------------------------------------------------------------------
# example config parses
# ---------------------------------------------------------------------------


def test_example_memory_yaml_parses():
    from shadow_tpu.config.options import load_config

    cfg = load_config(os.path.join(_REPO, "examples", "memory.yaml"))
    assert cfg.observability.memory
    assert cfg.pressure.policy == "escalate"
    assert cfg.pressure.memory_safety_factor >= 1.0


def test_memory_safety_factor_validated():
    from shadow_tpu.config.options import PressureOptions

    with pytest.raises(ConfigError, match="memory_safety_factor"):
        PressureOptions.from_dict(
            {"policy": "escalate", "memory_safety_factor": 0.5}
        )
