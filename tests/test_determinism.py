"""The determinism gate.

Reference analogue: src/test/determinism/ — run the identical config twice and
with both schedulers, then byte-compare outputs (SURVEY.md §4.3). Here the
compared artifact is the per-host rolling event digest (time, kind, order of
every popped event), and "both schedulers" becomes "1-device vs 8-device mesh":
sharding must not change any host's event history, packet-loss draws included.
"""

import numpy as np
import pytest

from tests.engine_harness import mk_hosts, run_sim

STOP = 1_000_000_000


def _digest(model, hosts, world, seed=1, **kw):
    _, stats, _ = run_sim(model, hosts, STOP, world=world, seed=seed, **kw)
    return np.asarray(stats.digest), stats


def _phold_hosts():
    return mk_hosts(16, {"mean_delay": "30 ms", "population": 2})


def test_two_runs_bit_identical():
    hosts = _phold_hosts()
    d1, s1 = _digest("phold", hosts, world=1, loss=0.1)
    d2, s2 = _digest("phold", hosts, world=1, loss=0.1)
    assert np.array_equal(d1, d2)
    assert int(s1.rounds) == int(s2.rounds)


def test_sharding_does_not_change_history():
    hosts = _phold_hosts()
    d1, s1 = _digest("phold", hosts, world=1, loss=0.1)
    d8, s8 = _digest("phold", hosts, world=8, loss=0.1)
    assert np.array_equal(d1, d8)
    # global event count identical too
    assert int(np.asarray(s1.events).sum()) == int(np.asarray(s8.events).sum())


def test_alltoall_exchange_matches_gather():
    """VERDICT r4 #4: the destination-sharded all-to-all exchange produces
    the SAME per-host histories as the replicated-gather exchange and as
    the 1-device run, with zero block sheds."""
    hosts = _phold_hosts()
    d1, s1 = _digest("phold", hosts, world=1, loss=0.1)
    da, sa = _digest("phold", hosts, world=8, loss=0.1, exchange="alltoall")
    assert np.array_equal(d1, da)
    assert int(np.asarray(sa.a2a_shed).sum()) == 0
    assert int(np.asarray(s1.events).sum()) == int(np.asarray(sa.events).sum())


def test_alltoall_exchange_tgen_tcp_mesh_invariant():
    """The TCP workload (bursty, retransmitting) over the all-to-all
    exchange stays bit-identical to the single-device run.

    Subprocess-isolated (tests/subproc.py): this is THE tier-1
    process-killer on this box — PR 7/8/9 all measured whole-suite runs
    segfaulting at exactly this leg (the documented jaxlib-0.4.37
    corruption, re-verified on unmodified HEAD each time), which turned
    one environment flake into DOTS_PASSED=0 for the entire gate. In a
    subprocess the corruption signature classifies as a skip (with
    retry + evidence) instead of killing pytest; a real divergence
    still fails loudly — the child's asserts surface as an ordinary
    rc=1 with output, which run_isolated never masks."""
    from tests.subproc import run_isolated_json

    out = run_isolated_json('''
import json
import numpy as np
from tests.engine_harness import mk_hosts, run_sim

hosts = mk_hosts(8, {"flow_segs": 24, "flows": 2, "cwnd_cap": 8,
                     "rto_min": "100 ms"})
stop = 20_000_000_000
_, s1, r1 = run_sim(
    "tgen_tcp", hosts, stop, world=1, loss=0.05, latency=10_000_000,
    sends_budget=24, qcap=64,
)
_, sa, ra = run_sim(
    "tgen_tcp", hosts, stop, world=8, loss=0.05, latency=10_000_000,
    sends_budget=24, qcap=64, exchange="alltoall",
)
print(json.dumps({
    "digest_equal": bool(np.array_equal(np.asarray(s1.digest),
                                        np.asarray(sa.digest))),
    "a2a_shed": int(np.asarray(sa.a2a_shed).sum()),
    "report_equal": r1 == ra,
}))
''', timeout=560)
    assert out["digest_equal"]
    assert out["a2a_shed"] == 0
    assert out["report_equal"]


def test_sharding_invariance_under_shaping_and_codel():
    """Token buckets + CoDel + loss together must stay mesh-invariant."""
    hosts = [
        dict(host_id=0, name="server", start_time=0, model_args={"role": "server"}),
        *(
            dict(
                host_id=i,
                name=f"c{i}",
                start_time=0,
                model_args={
                    "role": "client",
                    "peer": "server",
                    "interval": "5 ms",
                    "size_bytes": 2000,
                },
            )
            for i in range(1, 8)
        ),
    ]
    kw = dict(bw_bits=2_000_000, loss=0.05, use_codel=True)
    d1, _ = _digest("udp_echo", hosts, world=1, **kw)
    d8, _ = _digest("udp_echo", hosts, world=8, **kw)
    assert np.array_equal(d1, d8)


def test_seed_changes_history():
    hosts = _phold_hosts()
    d1, _ = _digest("phold", hosts, world=1, seed=1)
    d2, _ = _digest("phold", hosts, world=1, seed=2)
    assert not np.array_equal(d1, d2)


@pytest.mark.parametrize("world", [2, 4])
def test_other_mesh_shapes(world):
    hosts = _phold_hosts()
    d1, _ = _digest("phold", hosts, world=1)
    dw, _ = _digest("phold", hosts, world=world)
    assert np.array_equal(d1, dw)


def test_send_budget_drops_are_mesh_invariant():
    """Gossip with fanout over the per-host send budget: which packets get
    dropped must depend only on each host's own send count, never on shard
    composition (regression: the old per-shard outbox capacity made drops a
    function of mesh shape)."""
    hosts = mk_hosts(16, {"fanout": 6})
    hosts[0]["model_args"]["publisher"] = True
    kw = dict(sends_budget=4, runahead_floor=50_000_000)
    d1, s1 = _digest("gossip", hosts, world=1, **kw)
    d8, s8 = _digest("gossip", hosts, world=8, **kw)
    assert np.array_equal(d1, d8)
    dropped1 = np.asarray(s1.pkts_budget_dropped)
    assert dropped1.sum() > 0, "test must actually exceed the budget"
    np.testing.assert_array_equal(dropped1, np.asarray(s8.pkts_budget_dropped))
    # the shard buffer itself can never overflow under the budget
    assert int(np.asarray(s1.ob_dropped).sum()) == 0
    assert int(np.asarray(s8.ob_dropped).sum()) == 0


def test_mesh_invariance_at_scale():
    """VERDICT r2 weak #7: mesh determinism beyond toy sizes. 2048 PHOLD
    hosts with loss, multi-node routing via a 4-node ring — large enough
    that every shard handles hundreds of hosts and the exchange merge runs
    thousands of entries per round."""
    hosts = mk_hosts(2048, {"mean_delay": "60 ms", "population": 1})
    kw = dict(loss=0.02, runahead_floor=50_000_000)
    d1, s1 = _digest("phold", hosts, world=1, **kw)
    d8, s8 = _digest("phold", hosts, world=8, **kw)
    assert np.array_equal(d1, d8)
    assert int(np.asarray(s1.events).sum()) == int(np.asarray(s8.events).sum())
    assert int(np.asarray(s1.events).sum()) > 2048  # actually ran
