"""Config schema tests (reference analogue: src/test/config/)."""

import pytest

from shadow_tpu.config import load_config, merge_cli_overrides
from shadow_tpu.config.options import ConfigError

MINIMAL = """
general: {stop_time: 10s}
hosts:
  a: {network_node_id: 0, processes: [{model: timer}]}
"""


def test_minimal_defaults():
    cfg = load_config(MINIMAL, is_text=True)
    assert cfg.general.stop_time == 10_000_000_000
    assert cfg.general.seed == 1
    assert cfg.general.heartbeat_interval == 1_000_000_000  # default "1 s"
    assert cfg.experimental.scheduler == "tpu"
    assert cfg.hosts[0].name == "a"
    assert cfg.hosts[0].processes[0].model == "timer"


def test_heartbeat_explicit_and_null():
    cfg = load_config(
        "general: {stop_time: 1s, heartbeat_interval: 5s}\nhosts: {}", is_text=True
    )
    assert cfg.general.heartbeat_interval == 5_000_000_000
    cfg = load_config(
        "general: {stop_time: 1s, heartbeat_interval: null}\nhosts: {}", is_text=True
    )
    assert cfg.general.heartbeat_interval is None


def test_count_expansion():
    cfg = load_config(
        """
general: {stop_time: 1s}
hosts:
  client: {network_node_id: 2, count: 3, processes: [{model: timer}]}
""",
        is_text=True,
    )
    assert [h.name for h in cfg.hosts] == ["client1", "client2", "client3"]
    assert all(h.network_node_id == 2 for h in cfg.hosts)


def test_unknown_keys_named():
    with pytest.raises(ConfigError, match="sped"):
        load_config("general: {stop_time: 1s, sped: 2}", is_text=True)
    with pytest.raises(ConfigError, match="path.*model|model.*path"):
        load_config(
            "general: {stop_time: 1s}\nhosts: {a: {processes: [{}]}}", is_text=True
        )


def test_bandwidth_zero_is_explicit():
    cfg = load_config(
        """
general: {stop_time: 1s}
hosts:
  a: {bandwidth_down: 0, bandwidth_up: "10 Mbit", processes: [{model: timer}]}
""",
        is_text=True,
    )
    assert cfg.hosts[0].bandwidth_down == 0  # not silently None
    assert cfg.hosts[0].bandwidth_up == 10_000_000


def test_cli_overrides():
    cfg = load_config(MINIMAL, is_text=True)
    cfg = merge_cli_overrides(
        cfg,
        {
            "general.stop_time": "20s",
            "general.seed": "9",
            "general.heartbeat_interval": "2",
            "experimental.rounds_per_chunk": "16",
        },
    )
    assert cfg.general.stop_time == 20_000_000_000
    assert cfg.general.seed == 9
    assert cfg.general.heartbeat_interval == 2_000_000_000  # bare seconds, like YAML
    assert cfg.experimental.rounds_per_chunk == 16
    with pytest.raises(ConfigError, match="no_such"):
        merge_cli_overrides(cfg, {"general.no_such": "1"})


def test_cli_cpu_delay_unit_matches_yaml():
    """`cpu_delay: 100` in YAML and `--experimental.cpu_delay=100` must agree
    (both bare-ms); round 1 had the CLI path fall through to raw int(ns)."""
    cfg = load_config(
        "general: {stop_time: 1s}\nexperimental: {cpu_delay: 100}\n"
        "hosts: {a: {processes: [{model: timer}]}}",
        is_text=True,
    )
    assert cfg.experimental.cpu_delay == 100_000_000
    cfg2 = load_config(MINIMAL, is_text=True)
    cfg2 = merge_cli_overrides(cfg2, {"experimental.cpu_delay": "100"})
    assert cfg2.experimental.cpu_delay == cfg.experimental.cpu_delay
    cfg3 = merge_cli_overrides(
        load_config(MINIMAL, is_text=True), {"experimental.cpu_delay": "2 ms"}
    )
    assert cfg3.experimental.cpu_delay == 2_000_000


def test_host_option_defaults_cascade():
    cfg = load_config(
        """
general: {stop_time: 1s}
host_option_defaults: {pcap_enabled: true}
hosts:
  a: {processes: [{model: timer}]}
  b: {host_options: {pcap_enabled: false}, processes: [{model: timer}]}
""",
        is_text=True,
    )
    by_name = {h.name: h for h in cfg.hosts}
    assert by_name["a"].host_options.pcap_enabled is True
    assert by_name["b"].host_options.pcap_enabled is False


def test_static_shapes_autosize_from_host_count():
    """r4 (VERDICT r3 weak #9): 0-valued static-shape knobs derive from
    the host count — a plain 1M-host config gets the measured-good tight
    shapes (HBM fit + short chunks for the XLA while-loop pathology)
    without hand tuning; explicit settings always win."""
    from shadow_tpu.config.options import ExperimentalOptions

    ex = ExperimentalOptions()
    assert ex.resolve_shapes(10_000) == (64, 8, 64)
    assert ex.resolve_shapes(300_000) == (16, 4, 32)
    assert ex.resolve_shapes(1_000_000) == (4, 1, 8)
    ex.event_queue_capacity = 32
    ex.rounds_per_chunk = 16
    qcap, budget, rpc = ex.resolve_shapes(1_000_000)
    assert (qcap, budget, rpc) == (32, 1, 16)  # explicit wins, rest auto


def test_host_scheduler_and_pinning_knobs():
    """reference scheduler crate knobs: host_scheduler policy +
    use_cpu_pinning (affinity.c), with validation."""
    cfg = load_config(
        "general: {stop_time: 1s}\n"
        "experimental: {host_scheduler: per-host, use_cpu_pinning: true,"
        " host_workers: 3}\n"
        "hosts: {a: {processes: [{model: timer}]}}",
        is_text=True,
    )
    assert cfg.experimental.host_scheduler == "per-host"
    assert cfg.experimental.use_cpu_pinning is True
    assert cfg.experimental.host_workers == 3
    with pytest.raises(ConfigError, match="host_scheduler"):
        load_config(
            "general: {stop_time: 1s}\n"
            "experimental: {host_scheduler: bogus}\n"
            "hosts: {a: {processes: [{model: timer}]}}",
            is_text=True,
        )
