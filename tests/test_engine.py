"""Engine behavior tests: round loop, packet path, shaping, models.

Reference analogue: the per-subsystem unit tests plus the phold/tgen system
tests (SURVEY.md §4). Shapes are tiny — jit compile dominates test wall time.
"""

import numpy as np

from tests.engine_harness import mk_hosts, run_sim


def test_timer_counts_exact():
    hosts = mk_hosts(8, {"interval": "10 ms"})
    _, stats, report = run_sim("timer", hosts, 1_000_000_000)
    # fires at 0, 10ms, ..., 990ms -> exactly 100 per host; stop_time excluded
    assert report["min_fires"] == 100
    assert report["max_fires"] == 100
    assert int(np.asarray(stats.events).sum()) == 800
    assert int(np.asarray(stats.monotonic_violations).sum()) == 0


def test_stop_time_is_exclusive():
    hosts = mk_hosts(1, {"interval": "10 ms"})
    _, _, report = run_sim("timer", hosts, 10_000_000)  # one interval
    assert report["total_fires"] == 1  # t=0 only; t=10ms == stop not fired


def test_phold_conserves_population():
    hosts = mk_hosts(8, {"mean_delay": "50 ms", "population": 2})
    state, stats, report = run_sim("phold", hosts, 1_000_000_000)
    sent = int(np.asarray(stats.pkts_sent).sum())
    delivered = int(np.asarray(stats.pkts_delivered).sum())
    lost = int(np.asarray(stats.pkts_lost).sum())
    assert sent > 0
    assert lost == 0
    # every sent packet is delivered or still in flight at stop
    assert delivered <= sent
    assert sent - delivered < 64
    assert int(np.asarray(stats.events).sum()) == report["total_events"]


def test_echo_rtt_is_twice_latency():
    hosts = [
        dict(host_id=0, name="server", start_time=0, model_args={"role": "server"}),
        dict(
            host_id=1,
            name="c1",
            start_time=0,
            model_args={"role": "client", "peer": "server", "interval": "100 ms"},
        ),
    ]
    _, stats, report = run_sim("udp_echo", hosts, 1_000_000_000, latency=25_000_000)
    assert report["responses_received"] > 0
    assert abs(report["mean_rtt_ms"] - 50.0) < 1e-6
    assert abs(report["max_rtt_ms"] - 50.0) < 1e-6


def test_loss_drops_packets():
    hosts = [
        dict(host_id=0, name="server", start_time=0, model_args={"role": "server"}),
        *(
            dict(
                host_id=i,
                name=f"c{i}",
                start_time=0,
                model_args={"role": "client", "peer": "server", "interval": "20 ms"},
            )
            for i in range(1, 8)
        ),
    ]
    _, stats, report = run_sim("udp_echo", hosts, 2_000_000_000, loss=0.25)
    lost = int(np.asarray(stats.pkts_lost).sum())
    sent = int(np.asarray(stats.pkts_sent).sum())
    assert lost > 0
    assert 0.1 < lost / sent < 0.45  # ~25%
    assert report["responses_received"] < report["requests_sent"]


def test_bandwidth_shaping_inflates_rtt():
    fast = [
        dict(host_id=0, name="server", start_time=0, model_args={"role": "server"}),
        dict(
            host_id=1,
            name="c",
            start_time=0,
            model_args={
                "role": "client",
                "peer": "server",
                "interval": "10 ms",
                "size_bytes": 2500,
            },
        ),
    ]
    # demand 2 Mbit/s against a 1 Mbit/s shaped path vs an unshaped one
    _, _, shaped = run_sim("udp_echo", fast, 1_000_000_000, bw_bits=1_000_000)
    _, _, unshaped = run_sim("udp_echo", fast, 1_000_000_000, bw_bits=0)
    assert unshaped["mean_rtt_ms"] < shaped["mean_rtt_ms"] - 5
    assert abs(unshaped["mean_rtt_ms"] - 100.0) < 1e-6


def test_gossip_full_coverage():
    hosts = mk_hosts(32, {"fanout": 5})
    hosts[0]["model_args"]["publisher"] = True
    _, stats, report = run_sim("gossip", hosts, 5_000_000_000)
    assert report["coverage"] == 1.0
    assert 1 <= report["max_hops"] <= 10
    # each host forwards exactly fanout packets (incl. publisher)
    assert int(np.asarray(stats.pkts_sent).sum()) == 32 * 5
