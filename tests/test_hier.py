"""Hierarchical two-level exchange + lane diet (ISSUE 17, PR 17).

Two contracts pinned here:

* Exactness matrix — `exchange: hierarchical` (intra-shard (dst, t,
  order) compaction, then an inter-shard alltoall of compacted block
  prefixes) produces digests, per-host event counts, and EVERY drop
  counter bit-identical to the established engine, across echo/phold/
  tgen, flat and bucketed queue layouts, K in {1, 4}, gears on and off,
  and world in {1, 8}. The world-8 runs compare against the world-1
  full-width reference (the strongest form: digest invariance across
  MESH SHAPES, which the earlier exchange PRs already pinned for gather
  and alltoall — so hier == world-1 == alltoall transitively), plus one
  direct same-mesh hier-vs-alltoall leg including shed totals.

* Two-tier accounting — `stats.ici_intra` (local compaction staging,
  HBM) and `stats.ici_inter` (the wire) must each equal
  `exchange_tier_bytes_per_round(cfg)` x exchanges x world EXACTLY, and
  `stats.ici_bytes` must carry ONLY the inter tier: the hierarchy's
  claimed wire win is a model, and the counters are the model made
  observable.

* Lane diet — every exchange-wire lane's registered width round-trips
  its documented maximum occupancy losslessly (the proof obligation
  behind riding the wire at i32), while the 64-bit species (time/order/
  digest) genuinely cannot fit 32 bits.
"""

from __future__ import annotations

import dataclasses

import jax
import numpy as np
import pytest

from shadow_tpu.core import Engine
from shadow_tpu.core.engine import (
    exchange_ici_bytes_per_round,
    exchange_tier_bytes_per_round,
)
from shadow_tpu.core.gears import (
    GearController,
    resolve_gear_ladder,
    run_adaptive_chunk,
)
from tests.engine_harness import build_sim, mk_hosts

# the test_gears workload trio — but every case at 8 hosts so the SAME
# population runs on the 1- and 8-shard meshes (num_hosts must divide
# evenly over world; 1 host/shard is also the harshest compaction shape)
_CASES = {
    "phold": ("phold", mk_hosts(8, {"mean_delay": "20 ms", "population": 3}),
              300_000_000, dict(loss=0.1)),
    "echo": ("udp_echo",
             [dict(host_id=0, name="server", start_time=0,
                   model_args={"role": "server"})]
             + [dict(host_id=i, name=f"c{i}", start_time=0,
                     model_args={"role": "client", "peer": "server",
                                 "interval": "4 ms", "size_bytes": 2000})
                for i in range(1, 8)],
             200_000_000, dict(bw_bits=2_000_000, loss=0.05)),
    "tgen": ("tgen_tcp",
             mk_hosts(8, {"flow_segs": 8, "flows": 1, "cwnd_cap": 8,
                          "rto_min": "100 ms"}),
             1_500_000_000,
             dict(loss=0.05, latency=10_000_000, sends_budget=16)),
}


def _build(model, hosts, stop, world=1, **kw):
    cfg, m, params, mstate, events = build_sim(
        model, hosts, stop, world=world, **kw
    )
    mesh = None
    if world > 1:
        mesh = jax.sharding.Mesh(
            np.array(jax.devices()[:world]), ("hosts",)
        )
    eng = Engine(cfg, m, mesh)
    state, params = eng.init_state(params, mstate, events, seed=1)
    return cfg, eng, state, params


def _run_full(model, hosts, stop, world=1, **kw):
    cfg, eng, state, params = _build(model, hosts, stop, world, **kw)
    while not bool(state.done):
        state = eng.run_chunk(state, params)
    return cfg, state


# world-1 full-width reference runs, one per (case, qb, k) — every matrix
# leg below diffs against the same reference, so compute each once
_REF: dict[tuple, object] = {}


def _reference(case, qb, k):
    key = (case, qb, k)
    if key not in _REF:
        model, hosts, stop, kw = _CASES[case]
        _, state = _run_full(model, hosts, stop, queue_block=qb,
                             microstep_events=k, **kw)
        _REF[key] = state
    return _REF[key]


def _assert_identical(ref, hier):
    f = jax.device_get(ref.stats)
    g = jax.device_get(hier.stats)
    np.testing.assert_array_equal(np.asarray(f.digest), np.asarray(g.digest))
    np.testing.assert_array_equal(np.asarray(f.events), np.asarray(g.events))
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(ref.queue.dropped)),
        np.asarray(jax.device_get(hier.queue.dropped)),
    )
    for field in ("pkts_sent", "pkts_lost", "pkts_codel_dropped",
                  "pkts_budget_dropped", "pkts_delivered", "q_occ_hwm"):
        np.testing.assert_array_equal(
            np.asarray(getattr(f, field)), np.asarray(getattr(g, field)),
            err_msg=field,
        )
    # per-SHARD counters ([world]-shaped) compare by total across meshes
    assert (int(np.asarray(g.a2a_shed).sum())
            == int(np.asarray(f.a2a_shed).sum()))


def _assert_two_tier_model(cfg, state):
    """counter == model x exchanges x world, for BOTH tiers; ici_bytes
    carries only inter. One exchange per retired round plus the final
    probe round that discovers `done`."""
    s = jax.device_get(state.stats)
    exchanges = int(np.asarray(s.rounds)) + int(bool(state.done))
    intra_m, inter_m = exchange_tier_bytes_per_round(cfg)
    meas_intra = int(np.asarray(s.ici_intra).sum())
    meas_inter = int(np.asarray(s.ici_inter).sum())
    assert meas_intra == intra_m * exchanges * cfg.world
    assert meas_inter == inter_m * exchanges * cfg.world
    assert meas_inter == int(np.asarray(s.ici_bytes).sum())


def _matrix_params():
    """The acceptance matrix (test_runtime posture): the mixed-axis
    combos — (flat, k4) and (bucketed, k1) — carry the `slow` mark so
    the FULL cross product runs under `pytest -m ''` while tier-1 runs
    the aligned half (which still covers every axis value; the exchange
    sits upstream of the queue layout and the microstep fold, so the
    cross terms add composition coverage, not new exchange paths)."""
    out = []
    for case in sorted(_CASES):
        for k in (1, 4):
            for qb in (0, 8):
                aligned = (k == 1) == (qb == 0)
                out.append(pytest.param(
                    case, k, qb,
                    id=f"{case}-k{k}-{'flat' if qb == 0 else 'bucketed'}",
                    marks=() if aligned else (pytest.mark.slow,),
                ))
    return out


@pytest.mark.parametrize("case,k,qb", _matrix_params())
def test_hier_bit_identical_across_mesh(case, k, qb):
    """The acceptance gate: a world-8 hierarchical run is bit-identical
    to the world-1 full-width reference — digests, events, every drop
    counter — and its two tier counters reconcile exactly against
    `exchange_tier_bytes_per_round`."""
    model, hosts, stop, kw = _CASES[case]
    ref = _reference(case, qb, k)
    cfg, hier = _run_full(model, hosts, stop, world=8,
                          exchange="hierarchical", queue_block=qb,
                          microstep_events=k, **kw)
    _assert_identical(ref, hier)
    _assert_two_tier_model(cfg, hier)


def test_hier_vs_alltoall_same_mesh():
    """Direct same-mesh comparison (no transitivity): hierarchical and
    flat alltoall on the SAME 8-shard mesh agree on digests, events,
    drops, and shed totals."""
    model, hosts, stop, kw = _CASES["phold"]
    _, flat = _run_full(model, hosts, stop, world=8,
                        exchange="alltoall", **kw)
    cfg, hier = _run_full(model, hosts, stop, world=8,
                          exchange="hierarchical", **kw)
    _assert_identical(flat, hier)
    _assert_two_tier_model(cfg, hier)
    # the flat run carries no tier lanes (they exist only when traced)
    assert jax.device_get(flat.stats).ici_intra is None


@pytest.mark.parametrize("case", sorted(_CASES), ids=sorted(_CASES))
def test_hier_gears_bit_identical_with_forced_replay(case):
    """Gears ON: a gear ladder started at the BOTTOM rung (forcing real
    shed -> abort -> replay cycles through the hierarchical path, whose
    block size re-derives per gear) still finishes bit-identical to the
    world-1 full-width reference."""
    model, hosts, stop, kw = _CASES[case]
    ref = _reference(case, 0, 1)
    cfg, eng, state, params = _build(model, hosts, stop, world=8,
                                     exchange="hierarchical", **kw)
    ladder = resolve_gear_ladder("auto", cfg.sends_per_host_round)
    ctl = GearController(ladder)
    ctl.gear = ladder[0]
    while not bool(state.done):
        state, _, _ = run_adaptive_chunk(
            ctl, state, lambda st, g: eng.run_chunk_gear(st, params, g)
        )
    _assert_identical(ref, state)
    assert ctl.replays > 0
    # accepted chunks never shed (the aborted attempts were discarded)
    assert int(np.asarray(jax.device_get(state.stats).gear_shed).max()) == 0


@pytest.mark.parametrize("qb", [0, 8], ids=["flat", "bucketed"])
def test_hier_world1_degenerates_to_local_path(qb):
    """world=1 `hierarchical` is the same local gather-merge program as
    every other exchange kind: identical results, no tier lanes carried
    (hier_active is False), zero modeled bytes."""
    model, hosts, stop, kw = _CASES["phold"]
    cfg, hier = _run_full(model, hosts, stop, world=1,
                          exchange="hierarchical", queue_block=qb, **kw)
    ref = _reference("phold", qb, 1)
    _assert_identical(ref, hier)
    assert not cfg.hier_active
    assert jax.device_get(hier.stats).ici_intra is None
    assert exchange_tier_bytes_per_round(cfg) == (0, 0)


# ------------------------------------------------------------- cost model


def test_two_tier_model_gear_behavior():
    """The wire win is the GEAR-driven block shrink: at full width the
    hierarchical inter tier costs the flat alltoall's bytes plus one
    4-byte fill counter per peer (same auto block law), and every gear
    downshift shrinks both tiers below that — strictly below the
    gear-invariant flat wire once a gear is held."""
    model, hosts, stop, kw = _CASES["phold"]
    cfg, _, _, _ = _build(model, hosts, stop, world=8,
                          exchange="hierarchical", **kw)
    flat = exchange_ici_bytes_per_round(cfg, "alltoall")
    intra_full, inter_full = exchange_tier_bytes_per_round(cfg)
    assert inter_full == flat + (cfg.world - 1) * 4
    assert exchange_ici_bytes_per_round(cfg) == inter_full
    prev_inter = 0
    for g in resolve_gear_ladder("auto", cfg.sends_per_host_round)[:-1]:
        gcfg = dataclasses.replace(cfg, gear_cols=g)
        intra_g, inter_g = exchange_tier_bytes_per_round(gcfg)
        assert inter_g < flat, (g, inter_g, flat)
        assert intra_g < intra_full
        # wider gear, wider blocks — cost is monotone in the gear, and
        # every rung below the top undercuts the flat wire
        assert inter_g >= prev_inter
        prev_inter = inter_g


def test_effective_rounds_per_chunk_valve():
    """The rpc valve (satellite 1): untouched at <= 2^19 hosts, clamped
    to the microstep valve above — where the measured while-loop
    pathology (BASELINE.md r3) makes a big constant bound poison every
    dispatch."""
    from shadow_tpu.core import EngineConfig

    small = EngineConfig(num_hosts=1 << 19, stop_time=1,
                         rounds_per_chunk=64, queue_capacity=16)
    assert small.effective_rounds_per_chunk == 64
    big = EngineConfig(num_hosts=(1 << 19) + 1, stop_time=1,
                       rounds_per_chunk=64, queue_capacity=16)
    assert big.effective_rounds_per_chunk == 32  # 2 x queue_capacity
    pinned = EngineConfig(num_hosts=(1 << 19) + 1, stop_time=1,
                          rounds_per_chunk=64, queue_capacity=16,
                          microstep_limit=8)
    assert pinned.effective_rounds_per_chunk == 8
    tiny_rpc = EngineConfig(num_hosts=(1 << 19) + 1, stop_time=1,
                            rounds_per_chunk=4, queue_capacity=16)
    assert tiny_rpc.effective_rounds_per_chunk == 4  # clamp never raises


# -------------------------------------------------------------- lane diet


def test_lane_diet_roundtrip_at_max_occupancy():
    """The proof obligations behind the i32 wire diet, executed: each
    narrowed exchange-wire lane's documented MAXIMUM occupancy (from a
    deliberately large config) round-trips through its registered dtype
    losslessly, while the 64-bit wire species (time/order) genuinely
    exceed an i32 — so the diet is as narrow as exactness allows."""
    from shadow_tpu.core import EngineConfig
    from shadow_tpu.core.lanes import (
        BITS,
        EXCHANGE_WIRE_LANES,
        LANE_MIN_WIDTH_BITS,
        LANE_WIDTHS,
        ORDER_LANES,
        TIME_LANES,
    )

    cfg = EngineConfig(
        num_hosts=1 << 16, stop_time=3_600 * 10**9, world=8,
        sends_per_host_round=64, queue_capacity=256, queue_block=64,
        exchange="hierarchical",
    )
    rows = cfg.hosts_per_shard * cfg.sends_per_host_round
    bounds = {
        "dst": cfg.num_hosts - 1,
        "kind": 15,
        "payload": 2**31 - 1,  # i32 words by the payload contract
        "sent_round": cfg.sends_per_host_round,
        "count": rows,
        "bfill": cfg.queue_block,
        "seg_len": rows,
        "sent_counts": cfg.hier_block_size,
        "recv_counts": cfg.hier_block_size,
    }
    for lane, bound in bounds.items():
        dtype = np.dtype(LANE_WIDTHS[lane])
        # registered width respects the stated minimum...
        assert BITS[LANE_WIDTHS[lane]] >= LANE_MIN_WIDTH_BITS[lane], lane
        # ...and the max occupancy round-trips losslessly through it
        assert bound <= np.iinfo(dtype).max, lane
        assert int(np.asarray(bound, dtype=dtype)) == bound, lane
    # the 64-bit species genuinely need their width: one sim-hour of
    # nanoseconds and the packed 63-bit order key both overflow an i32
    i32max = np.iinfo(np.int32).max
    assert cfg.stop_time > i32max
    assert (1 << 62) > i32max  # order: (locality, src, seq) packed key
    for lane in EXCHANGE_WIRE_LANES:
        if LANE_MIN_WIDTH_BITS[lane] == 64:
            assert lane in TIME_LANES | ORDER_LANES, lane


def test_scale_example_parses():
    """examples/scale.yaml (the bench_scale config shape) parses and
    carries the documented knob pairing: hierarchical exchange + gears."""
    import os

    from shadow_tpu.config import load_config

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cfg = load_config(os.path.join(repo, "examples", "scale.yaml"))
    assert cfg.experimental.exchange == "hierarchical"
    assert cfg.experimental.merge_gears == "auto"


def test_lane_diet_table_consistency():
    """Structural half of shadowlint R7, pinned as a test too: every
    exchange-wire lane has a minimum-width entry, wire lanes whose
    minimum fits 32 bits actually RIDE at 32 (the diet is real, not
    aspirational), and nothing is registered narrower than exact."""
    from shadow_tpu.core.lanes import (
        BITS,
        EXCHANGE_WIRE_LANES,
        LANE_MIN_WIDTH_BITS,
        LANE_WIDTHS,
    )

    for lane in EXCHANGE_WIRE_LANES:
        assert lane in LANE_MIN_WIDTH_BITS, lane
        width = BITS[LANE_WIDTHS[lane]]
        assert width >= LANE_MIN_WIDTH_BITS[lane], lane
        if LANE_MIN_WIDTH_BITS[lane] <= 32:
            assert width == 32, (lane, "wire lane riding wider than exact")
    for lane, floor in LANE_MIN_WIDTH_BITS.items():
        assert BITS[LANE_WIDTHS[lane]] >= floor, lane
