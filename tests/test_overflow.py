"""Pressure tests at every fixed capacity (VERDICT r2 weak #6): each limit
must degrade counted-and-sane — bounded loss with a visible counter, or a
clean errno — never a wedge or silent corruption. Reference analogue: the
determinism suite + resource watchdogs (manager.rs:447-454)."""

from __future__ import annotations

import os

import pytest

from shadow_tpu.config.options import ConfigOptions
from shadow_tpu.cosim import HybridSimulation

MS = 1_000_000
SEC = 1_000_000_000
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from tests.subproc import native_plane_skip_reason

# toolchain-unavailable OR the shim-cannot-load (exit-97) container
# (tests/subproc.py native_plane_skip_reason classifies the signature)
_native_skip = native_plane_skip_reason()


@pytest.mark.skipif(_native_skip is not None, reason=str(_native_skip))
def test_thread_slot_exhaustion_is_eagain_and_recovers():
    """IPC_MAX_THREADS (32) bounds concurrent managed threads: the excess
    pthread_create calls fail with EAGAIN, and creation works again after
    slots recycle — no wedge, no crash."""
    from shadow_tpu.host import CpuHost, HostConfig
    from shadow_tpu.host.network import CpuNetwork
    from shadow_tpu.native_plane import IPC_MAX_THREADS, spawn_native

    host = CpuHost(HostConfig(name="h0", ip="10.0.0.1", seed=3, host_id=0))
    CpuNetwork([host], latency_ns=lambda s, d: MS)
    p = spawn_native(
        host,
        [os.path.join(REPO, "native", "build", "test_many_threads"), "40"],
    )
    host.execute(30 * SEC)
    assert p.exit_code == 0, b"".join(p.stderr)
    out = b"".join(p.stdout).decode()
    # main thread holds slot 0: 31 concurrent workers fit, 9 get EAGAIN
    assert f"created={IPC_MAX_THREADS - 1} eagain=9 other=0" in out
    assert "post-join create ok" in out


def _flood_cfg(n_clients: int, extra_exp: dict | None = None):
    return ConfigOptions.from_dict(
        {
            "general": {"stop_time": "4 s", "seed": 5},
            "network": {"graph": {"type": "1_gbit_switch"}},
            "experimental": extra_exp or {},
            "hosts": {
                "server": {
                    "network_node_id": 0,
                    "processes": [
                        {"path": "udp_echo_server", "args": ["port=9000"]}
                    ],
                },
                "client": {
                    "network_node_id": 0,
                    "count": n_clients,
                    "processes": [
                        {
                            # no expected_final_state: under ring overflow
                            # some clients legitimately never finish
                            "path": "udp_ping",
                            "args": ["server=server", "port=9000", "count=2"],
                            "expected_final_state": "running",
                        }
                    ],
                },
            },
        }
    )


def test_staging_cap_overflow_carries_no_loss():
    """More sends per window than the staging buffer holds: the bridge
    loops injection until drained, so a tiny cap loses NOTHING (it only
    costs extra inject dispatches) and results match a roomy cap."""

    def once(cap):
        sim = HybridSimulation(_flood_cfg(12), staging_cap=cap, world=1)
        r = sim.run()
        outs = {
            spec.name: b"".join(
                b"".join(p.stdout) for p in host.processes.values()
            )
            for spec, host in zip(sim.specs, sim.hosts)
        }
        return (
            r["determinism_digest"], r["packets_sent"],
            r["packets_delivered"], outs,
        )

    small = once(4)
    big = once(4096)
    assert small == big


def test_capture_ring_overflow_is_counted():
    """More same-window deliveries to one host than its capture ring holds:
    the excess is dropped AND counted (model_report capture_overflow_lost);
    the simulation still terminates cleanly."""
    from shadow_tpu.models.hybrid import HybridModel

    n = 150  # > capture_cap (128) arrivals at the server in one window
    sim = HybridSimulation(_flood_cfg(n), world=1)
    assert sim.model.capture_cap == 128
    r = sim.run()
    lost = r["model_report"]["capture_overflow_lost"]
    assert lost > 0
    # the shortfall is visible (not silent): fewer pings complete than were
    # sent, and the run still reaches stop_time
    assert r["packets_delivered"] < r["packets_sent"] or lost > 0
    assert r["simulated_seconds"] == 4.0


def test_event_queue_shed_policies_run_clean():
    """Tiny per-host event queues under flood: overflow is counted in
    queue_overflow_dropped for BOTH shed policies and the run terminates
    without monotonic violations."""
    for policy in ("urgency", "append"):
        sim = HybridSimulation(
            _flood_cfg(16, {"event_queue_capacity": 256,
                            "overflow_shed": policy}),
            world=1,
        )
        r = sim.run()
        assert r["packets_sent"] > 0
        # no wedge: the run reached stop_time and reported
        assert r["simulated_seconds"] == 4.0
