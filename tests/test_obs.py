"""Observability tests: pcap, strace, perf timers (SURVEY.md §5.1), incl.
the byte-identical-artifacts determinism gate (§4.3: the reference diffs
stdout + strace + pcaps between runs)."""

from __future__ import annotations

import os
import struct

from shadow_tpu.config.options import ConfigOptions
from shadow_tpu.cosim import HybridSimulation
from shadow_tpu.obs.pcap import PcapWriter, packet_bytes
from shadow_tpu.host.sockets import NetPacket


def _cfg(tmp, stop="2 s"):
    return ConfigOptions.from_dict(
        {
            "general": {"stop_time": stop, "seed": 21, "data_directory": str(tmp)},
            "network": {"graph": {"type": "1_gbit_switch"}},
            "experimental": {"strace_logging_mode": "deterministic"},
            "host_option_defaults": {"pcap_enabled": True},
            "hosts": {
                "server": {
                    "network_node_id": 0,
                    "processes": [{"path": "udp_echo_server", "args": ["port=9"]}],
                },
                "client": {
                    "network_node_id": 0,
                    "processes": [
                        {
                            "path": "udp_ping",
                            "args": ["server=server", "port=9", "count=3"],
                            "expected_final_state": {"exited": 0},
                        }
                    ],
                },
            },
        }
    )


def _read_pcap(path):
    with open(path, "rb") as f:
        hdr = f.read(24)
        magic, _, _, _, _, snap, link = struct.unpack("<IHHiIII", hdr)
        assert magic == 0xA1B2C3D4 and link == 1
        pkts = []
        while rec := f.read(16):
            sec, usec, caplen, origlen = struct.unpack("<IIII", rec)
            pkts.append((sec * 1_000_000 + usec, f.read(caplen)))
    return pkts


def test_pcap_and_strace_artifacts(tmp_path):
    cfg = _cfg(tmp_path / "a")
    sim = HybridSimulation(cfg)
    report = sim.run()
    sim.write_outputs(report=report)
    base = tmp_path / "a" / "hosts"
    eth = _read_pcap(base / "client" / "eth0.pcap")
    assert len(eth) == 6  # 3 pings out + 3 echoes in
    # frames parse as IPv4/UDP with the right ports
    t, frame = eth[0]
    assert frame[12:14] == b"\x08\x00"
    proto = frame[14 + 9]
    assert proto == 17
    src_port, dst_port = struct.unpack("!HH", frame[34:38])
    assert 9 in (src_port, dst_port)
    strace = list((base / "client").glob("*.strace"))
    assert strace, "no strace file written"
    text = strace[0].read_text()
    assert "sendto(" in text and "recvfrom(" in text and "= " in text
    assert report["perf"]["device_rounds"]["calls"] > 0


def test_observability_artifacts_bit_identical(tmp_path):
    def run(sub):
        cfg = _cfg(tmp_path / sub)
        sim = HybridSimulation(cfg)
        sim.write_outputs(report=sim.run())
        out = {}
        for root, _, files in os.walk(tmp_path / sub / "hosts"):
            for fn in files:
                if fn.endswith((".pcap", ".strace", ".stdout")):
                    p = os.path.join(root, fn)
                    rel = os.path.relpath(p, tmp_path / sub)
                    out[rel] = open(p, "rb").read()
        return out

    a, b = run("r1"), run("r2")
    assert a.keys() == b.keys()
    assert all(a[k] == b[k] for k in a), [
        k for k in a if a[k] != b[k]
    ]


def test_pcap_writer_tcp_frames(tmp_path):
    from shadow_tpu.tcp import Segment, SYN

    p = tmp_path / "x.pcap"
    w = PcapWriter(str(p))
    seg = Segment(SYN, seq=7, ack=0, wnd=100, src_port=1234, dst_port=80)
    w.write(
        1_500_000_000,
        NetPacket("10.0.0.1", 1234, "10.0.0.2", 80, 6, b"", seg),
    )
    w.close()
    pkts = _read_pcap(p)
    assert len(pkts) == 1
    t, frame = pkts[0]
    from shadow_tpu.simtime import EMUTIME_EPOCH_UNIX_SEC

    assert t == EMUTIME_EPOCH_UNIX_SEC * 1_000_000 + 1_500_000  # epoch 2000
    assert frame[14 + 9] == 6  # TCP
    seq = struct.unpack("!I", frame[38:42])[0]
    assert seq == 7


def test_sim_logger_format_and_backpressure():
    """SimLogger: sim-time-stamped, host-contexted records; flush thread
    drains; back-pressure blocks producers instead of growing unboundedly
    (shadow_logger.rs:17-60 thresholds recast)."""
    import io

    from shadow_tpu.obs.simlog import SimLogger, format_sim_time, parse_log

    assert format_sim_time(3_661_000_000_123) == "01:01:01.000000123"
    buf = io.StringIO()
    log = SimLogger(buf, level="info")
    log.log(1_500_000_000, "hostA", "debug", "filtered out")
    log.info(1_500_000_000, "hostA", "hello")
    log.warning(2_000_000_000, "hostB", "warn msg")
    log.close()
    lines = buf.getvalue().splitlines()
    assert lines == [
        "00:00:01.500000000 [info] [hostA] hello",
        "00:00:02.000000000 [warning] [hostB] warn msg",
    ]
    assert log.records == 2


def test_sim_logger_level_filtering_all_levels():
    """Level filtering across the whole LEVELS ladder: records strictly
    below the configured level never reach the queue (records counter
    included), records at/above always do."""
    import io

    from shadow_tpu.obs.simlog import LEVELS, SimLogger

    for i, lvl in enumerate(LEVELS):
        buf = io.StringIO()
        log = SimLogger(buf, level=lvl)
        for rec_lvl in LEVELS:
            log.log(1_000_000_000, "h", rec_lvl, f"m-{rec_lvl}")
        log.close()
        lines = buf.getvalue().splitlines()
        expect = LEVELS[i:]
        assert [ln.split()[1].strip("[]") for ln in lines] == list(expect)
        assert log.records == len(expect)
    # unknown levels default to info on both sides
    buf = io.StringIO()
    log = SimLogger(buf, level="bogus")
    log.log(0, "h", "debug", "filtered")
    log.log(0, "h", "mystery", "kept")  # unknown record level -> info
    log.close()
    assert log.records == 1


def test_sim_logger_backpressure_bounds_queue():
    """The back-pressure bound: with a slow writer the producer BLOCKS at
    BACKPRESSURE queued records instead of growing without bound
    (shadow_logger.rs's 1M-line cap recast) — observable as
    dropped_backpressure_waits > 0 — and no record is ever lost."""
    import time as _time

    class SlowSink:
        def __init__(self):
            self.lines = []

        def writelines(self, batch):
            self.lines.extend(batch)

        def flush(self):
            _time.sleep(0.02)  # producer outruns the flush thread

    from shadow_tpu.obs.simlog import SimLogger

    sink = SlowSink()
    log = SimLogger(sink, level="info")
    log.BACKPRESSURE = 8  # instance override: tiny bound, fast test
    n = 200
    max_seen = 0
    for i in range(n):
        log.info(i, "h", f"r{i}")
        max_seen = max(max_seen, len(log._q))
    log.close()
    assert log.records == n
    assert len(sink.lines) == n  # blocked, not dropped
    assert log.dropped_backpressure_waits > 0, "back-pressure never engaged"
    # the producer-side queue never exceeded the bound (+1 for the racing
    # append the flush thread may not have collected yet)
    assert max_seen <= log.BACKPRESSURE + 1


def test_perf_timers_report_shape():
    """PerfTimers: phase totals/counts in a stable report shape, nesting
    accumulates per phase, and a disabled timer reports nothing."""
    import time as _time

    from shadow_tpu.obs.perf import PerfTimers

    p = PerfTimers()
    for _ in range(3):
        with p.time("device_rounds"):
            _time.sleep(0.001)
    with p.time("host_plane"):
        with p.time("device_rounds"):  # nesting: distinct phases accumulate
            pass
    rep = p.report()
    assert sorted(rep) == ["device_rounds", "host_plane"]
    assert rep["device_rounds"]["calls"] == 4
    assert rep["host_plane"]["calls"] == 1
    assert rep["device_rounds"]["total_s"] >= 0.003
    assert set(rep["device_rounds"]) == {"total_s", "calls"}
    # exceptions still charge the phase (the finally path)
    try:
        with p.time("host_plane"):
            raise RuntimeError("boom")
    except RuntimeError:
        pass
    assert p.report()["host_plane"]["calls"] == 2

    off = PerfTimers(enabled=False)
    with off.time("x"):
        pass
    assert off.report() == {}


def test_shadow_log_written_and_parsed(tmp_path):
    """general.log_file: the co-sim writes a shadow.log with per-host
    process-exit records consumable by tools/parse_shadow.py."""
    from shadow_tpu.config.options import ConfigOptions
    from shadow_tpu.cosim import HybridSimulation
    from shadow_tpu.obs.simlog import parse_log

    cfg = ConfigOptions.from_dict(
        {
            "general": {
                "stop_time": "2 s",
                "seed": 3,
                "data_directory": str(tmp_path / "data"),
                "log_file": "shadow.log",
            },
            "network": {"graph": {"type": "1_gbit_switch"}},
            "hosts": {
                "server": {
                    "network_node_id": 0,
                    "processes": [
                        {"path": "udp_echo_server", "args": ["port=9000"]}
                    ],
                },
                "client": {
                    "network_node_id": 0,
                    "processes": [
                        {
                            "path": "udp_ping",
                            "args": ["server=server", "port=9000", "count=2"],
                            "expected_final_state": {"exited": 0},
                        }
                    ],
                },
            },
        }
    )
    sim = HybridSimulation(cfg, world=1)
    report = sim.run()
    assert report["process_failures"] == 0
    log_path = tmp_path / "data" / "shadow.log"
    assert log_path.exists()
    text = log_path.read_text()
    # the ping client exits mid-sim: its exit is logged with sim time +
    # host context
    assert "[client] process udp_ping" in text
    summary = parse_log(str(log_path))
    assert summary["per_host"].get("client", 0) >= 1
