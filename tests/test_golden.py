"""Dual-target gate: device engine vs the independent golden CPU engine.

The analogue of the reference's Linux-vs-Shadow dual test registration
(src/test/CMakeLists.txt add_linux_tests/add_shadow_tests) and its
two-scheduler determinism diff (src/test/determinism, 2a/2b vs 2c): the same
workload runs through two unrelated engine implementations and every per-host
digest and counter must agree bit-for-bit.
"""

import numpy as np
import pytest

from tests.engine_harness import mk_hosts, run_golden_sim, run_sim

STOP = 400_000_000  # golden is pure Python: keep sims short


def _compare(model, hosts, stop=STOP, **kw):
    state, stats, _ = run_sim(model, hosts, stop, world=1, **kw)
    gold = run_golden_sim(model, hosts, stop, **kw)
    np.testing.assert_array_equal(
        np.asarray(stats.digest), gold.digests, err_msg="digest mismatch"
    )
    for dev, g in [
        (stats.events, "events"),
        (stats.pkts_sent, "pkts_sent"),
        (stats.pkts_lost, "pkts_lost"),
        (stats.pkts_delivered, "pkts_delivered"),
        (stats.pkts_codel_dropped, "pkts_codel_dropped"),
        (stats.pkts_budget_dropped, "pkts_budget_dropped"),
        (stats.monotonic_violations, "monotonic_violations"),
    ]:
        np.testing.assert_array_equal(np.asarray(dev), gold.stats[g], err_msg=g)
    np.testing.assert_array_equal(
        np.asarray(state.queue.dropped), gold.stats["dropped"], err_msg="dropped"
    )
    assert int(stats.rounds) == gold.rounds
    return gold


def test_timer_matches():
    _compare("timer", mk_hosts(6, {"interval": "7 ms"}))


def test_phold_matches():
    # float path (exponential holding delay) + random peers + loss draws
    _compare("phold", mk_hosts(10, {"mean_delay": "20 ms", "population": 2}), loss=0.1)


def test_echo_under_shaping_matches():
    # token buckets on both directions + CoDel + loss: the full ingress/egress
    # pipeline arithmetic must agree scalar-vs-vectorized
    hosts = [
        dict(host_id=0, name="server", start_time=0, model_args={"role": "server"}),
        *(
            dict(
                host_id=i,
                name=f"c{i}",
                start_time=0,
                model_args={
                    "role": "client",
                    "peer": "server",
                    "interval": "4 ms",
                    "size_bytes": 2000,
                },
            )
            for i in range(1, 6)
        ),
    ]
    gold = _compare("udp_echo", hosts, bw_bits=2_000_000, loss=0.05, use_codel=True)
    assert gold.stats["pkts_codel_dropped"].sum() > 0 or gold.stats["pkts_lost"].sum() > 0


def test_gossip_budget_matches():
    # send-budget drops + queue-capacity overflow paths
    hosts = mk_hosts(12, {"fanout": 6})
    hosts[0]["model_args"]["publisher"] = True
    gold = _compare(
        "gossip", hosts, sends_budget=4, runahead_floor=50_000_000, qcap=16
    )
    assert gold.stats["pkts_budget_dropped"].sum() > 0


def test_golden_vs_multishard():
    """Transitivity spot check: golden == device(world=4) directly."""
    hosts = mk_hosts(8, {"mean_delay": "20 ms", "population": 1})
    _, stats, _ = run_sim("phold", hosts, STOP, world=4, loss=0.1)
    gold = run_golden_sim("phold", hosts, STOP, loss=0.1)
    np.testing.assert_array_equal(np.asarray(stats.digest), gold.digests)


def test_cpu_delay_matches():
    """The CPU busy-horizon model (cpu_delay) must agree between the device
    engine and the golden engine: busy-shifted execution times feed the
    digest, the window barrier, and every downstream timestamp (removes the
    round-1 carve-out that rejected cpu_delay under cpu-reference)."""
    # dense timers so the delay actually defers events within windows
    _compare(
        "timer", mk_hosts(6, {"interval": "2 ms"}), cpu_delay_ns=500_000
    )
    # and with packet traffic + shaping in the mix
    _compare(
        "phold", mk_hosts(8, {"mean_delay": "15 ms", "population": 2}),
        loss=0.05, cpu_delay_ns=300_000,
    )


def test_jitter_matches():
    """Per-packet latency jitter (graph `jitter` attribute — the reference
    parses it, graph/mod.rs:87-92; here it is applied): device and golden
    must agree on the jittered arrival times, and the lookahead bound must
    use latency - jitter."""
    gold = _compare(
        "phold", mk_hosts(8, {"mean_delay": "20 ms", "population": 2}),
        jitter=10_000_000, latency=40_000_000,
    )
    assert gold.stats["pkts_delivered"].sum() > 0
