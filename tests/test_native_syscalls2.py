"""Round-3 native syscall surface: uio/msg, select, dup2/socketpair/ioctl,
execve (reference: handler/uio.c, select.c, unistd dup arms, the execve arm
at handler/mod.rs:401, and the matching src/test binaries)."""

from __future__ import annotations

import os

import pytest

from shadow_tpu.host import CpuHost, HostConfig
from shadow_tpu.host.network import CpuNetwork

from tests.subproc import native_plane_skip_reason

# toolchain-unavailable OR the shim-cannot-load (exit-97) container
# (tests/subproc.py native_plane_skip_reason classifies the signature)
_skip = native_plane_skip_reason()
pytestmark = pytest.mark.skipif(_skip is not None, reason=str(_skip))

from shadow_tpu.native_plane import spawn_native  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
UIO = os.path.join(REPO, "native", "build", "test_uio")
SELECT = os.path.join(REPO, "native", "build", "test_select")
MISC = os.path.join(REPO, "native", "build", "test_misc")
EXEC = os.path.join(REPO, "native", "build", "test_exec")

MS = 1_000_000
SEC = 1_000_000_000


def two_hosts(lat_ms=25, seed=7):
    hosts = [
        CpuHost(HostConfig(name=f"h{i}", ip=f"10.0.0.{i + 1}", seed=seed, host_id=i))
        for i in range(2)
    ]
    net = CpuNetwork(hosts, latency_ns=lambda s, d: lat_ms * MS)
    return hosts, net


def test_sendmsg_recvmsg_udp():
    hosts, net = two_hosts()
    srv = spawn_native(hosts[0], [UIO, "server", "9000", "3"])
    cli = spawn_native(
        hosts[1], [UIO, "client", "10.0.0.1", "9000", "3"], start_time=50 * MS
    )
    net.run(5 * SEC)
    assert srv.exit_code == 0, b"".join(srv.stderr)
    assert cli.exit_code == 0, b"".join(cli.stderr)
    out = b"".join(cli.stdout).decode()
    assert "reply 2: part1-2|part2-2 from port 9000" in out


def test_readv_writev_tcp():
    hosts, net = two_hosts()
    srv = spawn_native(hosts[0], [UIO, "tserver", "9001"])
    cli = spawn_native(
        hosts[1], [UIO, "tclient", "10.0.0.1", "9001"], start_time=50 * MS
    )
    net.run(8 * SEC)
    assert srv.exit_code == 0, b"".join(srv.stderr)
    assert cli.exit_code == 0, b"".join(cli.stderr)
    assert b"readv total 32" in b"".join(srv.stdout)


def test_select_multiplexing():
    hosts, net = two_hosts()
    srv = spawn_native(hosts[0], [SELECT, "server", "9100", "4"])
    cli = spawn_native(
        hosts[1], [SELECT, "client", "10.0.0.1", "9100", "4"],
        start_time=50 * MS,
    )
    net.run(10 * SEC)
    assert srv.exit_code == 0, b"".join(srv.stderr)
    assert cli.exit_code == 0, b"".join(cli.stderr)
    out = b"".join(srv.stdout).decode()
    assert out.count("echo via first") == 2
    assert out.count("echo via second") == 2


def test_select_timeout_fires():
    # a select with no traffic must time out in SIMULATED time, not hang
    hosts, net = two_hosts()
    srv = spawn_native(hosts[0], [SELECT, "server", "9200", "1"])
    net.run(20 * SEC)
    # 5 two-second timeouts and the server gives up with exit 1
    assert srv.exit_code == 1


def test_dup_socketpair_ioctl_misc():
    hosts, net = two_hosts()
    p = spawn_native(hosts[0], [MISC])
    net.run(2 * SEC)
    assert p.exit_code == 0, b"".join(p.stderr)
    out = b"".join(p.stdout)
    assert b"misc ok" in out
    # dup2(1, 2) redirects stderr into the stdout capture (2>&1)
    assert b"redirected-to-stdout" in out
    assert b"redirected-to-stdout" not in b"".join(p.stderr)


def test_execve_respawn():
    hosts, net = two_hosts()
    p = spawn_native(hosts[0], [EXEC])
    net.run(5 * SEC)
    assert p.exit_code == 0, b"".join(p.stderr)
    out = b"".join(p.stdout).decode()
    assert "parent saw exec'd child exit 42" in out


def test_execve_replaces_image_in_place():
    # exec WITHOUT fork: same virtual process, new image, stdout capture
    # spans both images
    hosts, net = two_hosts()
    import subprocess
    sh = "/bin/sh"
    p = spawn_native(hosts[0], [sh, "-c", f"exec {EXEC} worker direct"])
    net.run(5 * SEC)
    assert p.exit_code == 42, (p.exit_code, b"".join(p.stderr))
    assert b"worker pid=" in b"".join(p.stdout)


UNIXNL = os.path.join(REPO, "native", "build", "test_unix_netlink")


def test_unix_sockets_cross_process():
    """AF_UNIX abstract-namespace stream sockets between two native
    processes on one host (bind/listen/fork/connect/accept + EADDRINUSE;
    reference socket/unix.rs + abstract_unix_ns.rs)."""
    hosts, net = two_hosts()
    p = spawn_native(hosts[0], [UNIXNL])
    net.run(10 * SEC)
    assert p.exit_code == 0, b"".join(p.stderr)
    assert b"unix ok" in b"".join(p.stdout)


def test_netlink_rtm_getaddr_dump():
    """Raw rtnetlink RTM_GETADDR dump answered with the simulated lo+eth0
    (reference socket/netlink.rs)."""
    hosts, net = two_hosts()
    p = spawn_native(hosts[0], [UNIXNL, "netlink"])
    net.run(10 * SEC)
    assert p.exit_code == 0, b"".join(p.stderr)
    out = b"".join(p.stdout).decode()
    assert "addr lo 127.0.0.1" in out
    assert "addr eth0 10.0.0.1" in out
    assert "netlink ok found=2" in out


def test_unix_dgram_sockets():
    """AF_UNIX datagram sockets: named (syslog /dev/log shape) with
    preserved message boundaries, plus dgram socketpair."""
    hosts, net = two_hosts()
    p = spawn_native(hosts[0], [UNIXNL, "dgram"])
    net.run(5 * SEC)
    assert p.exit_code == 0, b"".join(p.stderr)
    assert b"dgram ok" in b"".join(p.stdout)
