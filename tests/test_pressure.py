"""Pressure plane (`pressure:` config block, PR 8): exactness-gated
capacity migration, drop-free escalation, abort policy, OOM fallback,
and cross-capacity checkpoint restore.

The acceptance contract mirrors the gear plane's: an escalate-mode run
that WOULD drop at the seed capacity finishes with zero drops and a
digest bit-identical to a run launched at the final shape (with the
valve pins Engine.run_chunk_resized documents); `pressure: drop` (the
default) traces no pressure code at all; a forced-OOM fallback path runs
without killing the process. Engine-harness runs only — the stable
in-process path on this box (CHANGES.md env notes)."""

from __future__ import annotations

import dataclasses
import types

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shadow_tpu.config.options import ConfigError, PressureOptions
from shadow_tpu.core import Engine
from shadow_tpu.core.pressure import (
    PressureAbort,
    ResilienceController,
    resolve_ladder,
)
from shadow_tpu.ops.events import (
    ORDER_MAX,
    bucket_rebuild,
    grow_bucket_queue,
    grow_queue,
    make_bucket_queue,
    make_queue,
    migrate_queue,
    migration_fits,
    pack_order,
    q_clear_popped,
    q_len,
    q_pop_k,
    q_pop_min,
    q_push_many,
)
from shadow_tpu.simtime import TIME_MAX
from tests.engine_harness import build_sim, mk_hosts

MS = 1_000_000


# ---------------------------------------------------------------------------
# grow-op property tests: any push/pop sequence replayed across C < C'
# ---------------------------------------------------------------------------


def _mk(h, cap, block):
    return (
        make_bucket_queue(h, cap, block) if block else make_queue(h, cap)
    )


def _random_ops(rng, h, n_steps):
    """A reproducible (op, args) schedule heavy enough to overflow small
    capacities: bursts of pushes with unique order keys + windowed pops."""
    ops = []
    seq = 0
    t_base = 0
    for _ in range(n_steps):
        kind = rng.integers(0, 3)
        if kind == 0:  # push burst
            burst = []
            for _ in range(int(rng.integers(1, 4))):
                t = t_base + int(rng.integers(0, 50)) * MS
                burst.append((t, seq))
                seq += 1
            ops.append(("push", burst))
        elif kind == 1:  # windowed pop
            ops.append(("pop", t_base + int(rng.integers(10, 80)) * MS))
        else:  # K-way pop
            ops.append(("popk", t_base + int(rng.integers(10, 80)) * MS))
        t_base += int(rng.integers(0, 20)) * MS
    return ops


def _apply(q, op, k):
    """Apply one schedule step; returns (q', observation tuple)."""
    name, arg = op
    h = q.t.shape[0]
    if name == "push":
        pushes = []
        for t, seq in arg:
            mask = jnp.ones((h,), bool)
            order = pack_order(1, jnp.arange(h, dtype=jnp.int64), seq)
            pushes.append((
                mask,
                jnp.full((h,), t, jnp.int64),
                order,
                jnp.full((h,), 3, jnp.int32),
                jnp.full((h, 4), seq, jnp.int32),
            ))
        q = q_push_many(q, pushes)
        return q, ("push", np.asarray(q.dropped).copy())
    if name == "pop":
        q, ev, active = q_pop_min(q, jnp.int64(arg))
        return q, (
            "pop", np.asarray(ev.t).copy(), np.asarray(ev.order).copy(),
            np.asarray(active).copy(),
        )
    popped = q_pop_k(q, jnp.int64(arg), k)
    m = jnp.sum(popped.active.astype(jnp.int32), axis=1)
    q = q_clear_popped(q, popped, m)
    return q, (
        "popk", np.asarray(popped.t).copy(), np.asarray(popped.order).copy(),
        np.asarray(popped.active).copy(),
    )


@pytest.mark.parametrize("block", [0, 4], ids=["flat", "bucketed"])
@pytest.mark.parametrize("k", [1, 4], ids=["k1", "k4"])
def test_grow_midstream_equals_big_capacity(block, k):
    """The migration exactness property: run a random push/pop schedule;
    path A starts at C=8 and GROWS to C'=16 at a drop-free cut point,
    path B runs the whole schedule at C'=16. Every observation after the
    cut — popped events, actives, drop deltas, occupancies — must be
    bit-identical (before the cut the small queue may drop; the cut is
    chosen after a drain so both paths hold the same event multiset)."""
    rng = np.random.default_rng(1234 + block * 10 + k)
    h, c_small, c_big = 5, 8, 16
    ops = _random_ops(rng, h, 24)
    # phase 1 is drop-free by construction: small bursts + draining pops
    warm = [("push", [(5 * MS, 900), (7 * MS, 901)]), ("pop", 100 * MS)]

    qa = _mk(h, c_small, block)
    for op in warm:
        qa, _ = _apply(qa, op, k)
    drops_a0 = np.asarray(qa.dropped).copy()
    assert drops_a0.sum() == 0, "warm phase must be drop-free"
    qb = _mk(h, c_big, block)
    for op in warm:
        qb, _ = _apply(qb, op, k)
    # the cut: grow path A to the big capacity
    qa = (
        grow_bucket_queue(qa, c_big) if block else grow_queue(qa, c_big)
    )
    np.testing.assert_array_equal(np.asarray(q_len(qa)), np.asarray(q_len(qb)))
    for i, op in enumerate(ops):
        qa, obs_a = _apply(qa, op, k)
        qb, obs_b = _apply(qb, op, k)
        for x, y in zip(obs_a, obs_b):
            if isinstance(x, str):
                assert x == y
            else:
                np.testing.assert_array_equal(x, y, err_msg=f"op {i} {op[0]}")
        np.testing.assert_array_equal(
            np.asarray(qa.dropped), np.asarray(qb.dropped), err_msg=f"op {i}"
        )
        np.testing.assert_array_equal(
            np.asarray(q_len(qa)), np.asarray(q_len(qb)), err_msg=f"op {i}"
        )


def test_grow_preserves_events_and_caches():
    """Growth pads empty sentinel columns only: the live slots are
    untouched, and a grown bucketed queue's caches equal a wholesale
    rebuild of its slab (the block-min invariant holds post-grow)."""
    q = make_queue(3, 4)
    q = q_push_many(q, [(
        jnp.ones((3,), bool), jnp.full((3,), 7 * MS, jnp.int64),
        pack_order(1, jnp.arange(3, dtype=jnp.int64), 0),
        jnp.full((3,), 2, jnp.int32), jnp.zeros((3, 4), jnp.int32),
    )])
    g = grow_queue(q, 8)
    assert g.t.shape == (3, 8)
    np.testing.assert_array_equal(np.asarray(g.t[:, :4]), np.asarray(q.t))
    assert (np.asarray(g.t[:, 4:]) == TIME_MAX).all()
    assert (np.asarray(g.order[:, 4:]) == ORDER_MAX).all()
    bq = bucket_rebuild(q, 2)
    gb = grow_bucket_queue(bq, 8)
    ref = bucket_rebuild(gb, gb.block)
    for field in ("bt", "bo", "bfill"):
        np.testing.assert_array_equal(
            np.asarray(getattr(gb, field)), np.asarray(getattr(ref, field)),
        )


def test_shrink_compacts_and_refuses_overfull():
    """Shrink compacts live events to the front (stable) and the
    `migration_fits` predicate names exactly the hosts that cannot."""
    q = make_queue(2, 8)
    pushes = []
    for s in range(5):
        mask = jnp.asarray([True, s < 2])  # host 0: 5 live, host 1: 2
        pushes.append((
            mask, jnp.full((2,), (s + 1) * MS, jnp.int64),
            pack_order(1, jnp.arange(2, dtype=jnp.int64), s),
            jnp.full((2,), 1, jnp.int32), jnp.zeros((2, 4), jnp.int32),
        ))
    q = q_push_many(q, pushes)
    fits = np.asarray(migration_fits(q, 4))
    np.testing.assert_array_equal(fits, [False, True])
    assert np.asarray(migration_fits(q, 5)).all()
    small = migrate_queue(q, 5)
    assert small.t.shape == (2, 5)
    # identical pop sequence off the compacted slab
    a, b = q, small
    for _ in range(5):
        a, ev_a, act_a = q_pop_min(a, jnp.int64(100 * MS))
        b, ev_b, act_b = q_pop_min(b, jnp.int64(100 * MS))
        np.testing.assert_array_equal(np.asarray(ev_a.t), np.asarray(ev_b.t))
        np.testing.assert_array_equal(
            np.asarray(ev_a.order), np.asarray(ev_b.order)
        )
        np.testing.assert_array_equal(np.asarray(act_a), np.asarray(act_b))


def test_migrate_queue_validation():
    q = make_queue(2, 8)
    with pytest.raises(ValueError, match="new_capacity"):
        migrate_queue(q, 0)
    with pytest.raises(ValueError, match="block"):
        migrate_queue(q, 8, block=3)
    with pytest.raises(ValueError, match="exceed"):
        grow_queue(q, 8)


# ---------------------------------------------------------------------------
# escalate end-to-end: digest gate vs a run launched at the final shape
# ---------------------------------------------------------------------------

_CASES = {
    "phold": ("phold", mk_hosts(8, {"mean_delay": "20 ms", "population": 3}),
              300_000_000, dict(loss=0.1)),
    "echo": ("udp_echo",
             [dict(host_id=0, name="server", start_time=0,
                   model_args={"role": "server"})]
             + [dict(host_id=i, name=f"c{i}", start_time=0,
                     model_args={"role": "client", "peer": "server",
                                 "interval": "4 ms", "size_bytes": 2000})
                for i in range(1, 5)],
             200_000_000, dict(bw_bits=2_000_000, loss=0.05)),
    "tgen": ("tgen_tcp",
             mk_hosts(5, {"flow_segs": 8, "flows": 1, "cwnd_cap": 8,
                          "rto_min": "100 ms"}),
             1_500_000_000,
             dict(loss=0.05, latency=10_000_000, sends_budget=16)),
}


def _build(model, hosts, stop, pressure_abort=False, **kw):
    cfg, m, params, mstate, events = build_sim(
        model, hosts, stop, rounds_per_chunk=16, **kw
    )
    if pressure_abort:
        cfg = dataclasses.replace(cfg, pressure_abort=True)
    eng = Engine(cfg, m)
    state, params = eng.init_state(params, mstate, events, seed=1)
    return cfg, eng, state, params


def _run_escalated(model, hosts, stop, policy="escalate", **kw):
    cfg, eng, state, params = _build(
        model, hosts, stop, pressure_abort=True, **kw
    )
    rc = ResilienceController(
        pressure=PressureOptions(policy=policy, max_capacity=256,
                                 max_outbox=64),
        queue_block=cfg.queue_block,
    )
    chunks = 0
    while not bool(state.done):
        state, _, _ = rc.run_chunk(
            state,
            lambda s, g, c, b: eng.run_chunk_resized(s, params, g, c, b),
        )
        chunks += 1
        assert chunks < 500
    return cfg, state, rc


def _assert_drop_free_and_identical(state, ref):
    s, r = jax.device_get(state.stats), jax.device_get(ref.stats)
    np.testing.assert_array_equal(np.asarray(s.digest), np.asarray(r.digest))
    np.testing.assert_array_equal(np.asarray(s.events), np.asarray(r.events))
    for field in ("pkts_sent", "pkts_lost", "pkts_delivered",
                  "pkts_budget_dropped"):
        np.testing.assert_array_equal(
            np.asarray(getattr(s, field)), np.asarray(getattr(r, field)),
            err_msg=field,
        )
    assert int(np.asarray(jax.device_get(state.queue.dropped)).sum()) == 0
    assert int(np.asarray(s.pkts_budget_dropped).sum()) == 0
    press = np.asarray(s.pressure) if s.pressure is not None else None
    assert press is None or int(press.max()) == 0


@pytest.mark.parametrize("qb", [0, 4], ids=["flat", "bucketed"])
@pytest.mark.parametrize("k", [1, 4], ids=["k1", "k4"])
@pytest.mark.parametrize("case", sorted(_CASES), ids=sorted(_CASES))
def test_escalate_drop_free_and_bit_identical(case, k, qb):
    """The acceptance gate: starting from a queue capacity that WOULD
    drop, the escalate policy finishes with zero drops and digests /
    events / drop counters bit-identical to a run LAUNCHED at the final
    shape (same pinned valve), having genuinely regrown along the way."""
    model, hosts, stop, kw = _CASES[case]
    # per-case undersized start capacity (small enough that the workload
    # GENUINELY pressures it; phold's population-3 steady state fits 8)
    qcap0 = {"phold": 4, "echo": 8, "tgen": 4}[case]
    cfg0, state, rc = _run_escalated(
        model, hosts, stop, qcap=qcap0, queue_block=qb,
        microstep_events=k, **kw,
    )
    cap_f = state.queue.t.shape[1]
    budget_f = state.outbox.t.shape[1]
    assert rc.regrows + rc.proactive_regrows > 0, "nothing escalated"
    # reference: LAUNCHED at the final shape with the escalation's pins
    # (valve = base effective limit; auto max_round_inserts follows cap)
    _, eng_r, ref, params_r = _build(
        model, hosts, stop,
        qcap=cap_f, queue_block=qb, microstep_events=k,
        **{**kw, "sends_budget": budget_f},
    )
    eng_r.cfg = dataclasses.replace(
        eng_r.cfg, microstep_limit=cfg0.effective_microstep_limit,
        max_round_inserts=cap_f if cfg0.max_round_inserts == qcap0
        else cfg0.max_round_inserts,
    )
    eng_r._build_run_chunk()
    while not bool(ref.done):
        ref = eng_r.run_chunk(ref, params_r)
    _assert_drop_free_and_identical(state, ref)


def test_escalate_mesh_invariant():
    """world=8 dryrun: the pressure signal is psum'd, so the first-drop
    abort is mesh-uniform, migration re-shards onto the mesh specs, and
    the escalated result matches the single-device run launched at the
    final shape."""
    model, hosts, stop, kw = _CASES["phold"]
    if jax.device_count() < 8:
        pytest.skip("needs the 8-virtual-device conftest mesh")
    cfg, m, params, mstate, events = build_sim(
        model, hosts, stop, world=8, qcap=4, rounds_per_chunk=16, **kw
    )
    cfg = dataclasses.replace(cfg, pressure_abort=True)
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:8]), ("hosts",))
    eng = Engine(cfg, m, mesh)
    state, params = eng.init_state(params, mstate, events, seed=1)
    specs = jax.tree.map(
        lambda s: jax.sharding.NamedSharding(mesh, s), eng.state_specs()
    )
    rc = ResilienceController(
        pressure=PressureOptions(policy="escalate", max_capacity=256),
        reshard=lambda st: jax.device_put(st, specs),
    )
    while not bool(state.done):
        state, _, _ = rc.run_chunk(
            state,
            lambda s, g, c, b: eng.run_chunk_resized(s, params, g, c, b),
        )
    assert rc.regrows + rc.proactive_regrows > 0
    assert int(np.asarray(jax.device_get(state.queue.dropped)).sum()) == 0
    cap_f = state.queue.t.shape[1]
    _, eng_r, ref, params_r = _build(
        model, hosts, stop, qcap=cap_f, **kw
    )
    eng_r.cfg = dataclasses.replace(
        eng_r.cfg, microstep_limit=cfg.effective_microstep_limit,
        max_round_inserts=cap_f,
    )
    eng_r._build_run_chunk()
    while not bool(ref.done):
        ref = eng_r.run_chunk(ref, params_r)
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(state.stats.digest)),
        np.asarray(jax.device_get(ref.stats.digest)),
    )


def test_gears_only_controller_dispatches_base_shapes():
    """Regression (r8 review): a gears-only ResilienceController (no
    pressure block — exactly how Simulation.run and bench wire it) never
    reads the state's shapes and passes capacity/budget 0 to the
    dispatch; Engine.run_chunk_resized must treat 0 as the BASE shape,
    not compile a zero-width program."""
    from shadow_tpu.core.gears import GearController, resolve_gear_ladder

    model, hosts, stop, kw = _CASES["phold"]
    cfg, eng, state, params = _build(model, hosts, stop, qcap=16, **kw)
    ladder = resolve_gear_ladder([2, 4], cfg.sends_per_host_round)
    rc = ResilienceController(gearctl=GearController(ladder))
    while not bool(state.done):
        state, _, _ = rc.run_chunk(
            state,
            lambda s, g, c, b: eng.run_chunk_resized(s, params, g, c, b),
        )
    _, eng_r, refst, params_r = _build(model, hosts, stop, qcap=16, **kw)
    while not bool(refst.done):
        refst = eng_r.run_chunk(refst, params_r)
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(state.stats.digest)),
        np.asarray(jax.device_get(refst.stats.digest)),
    )


def test_oom_on_outbox_only_growth_falls_back():
    """Regression (r8 review): an OOM on a program grown only on the
    OUTBOX axis must fall back (and poison the outbox rung) instead of
    re-raising — and the poisoned rung corners the next budget drop into
    a graceful PressureAbort."""
    model, hosts, stop, kw = _CASES["phold"]
    kw = {**kw, "sends_budget": 1}
    cfg, eng, state, params = _build(
        model, hosts, stop, qcap=16, pressure_abort=True, **kw
    )
    rc = ResilienceController(
        pressure=PressureOptions(policy="escalate", max_outbox=8),
    )

    class XlaRuntimeError(RuntimeError):
        pass

    def dispatch(s, g, c, b):
        if b > 1:
            raise XlaRuntimeError("RESOURCE_EXHAUSTED: outbox slab")
        return eng.run_chunk_resized(s, params, g, c, b)

    with pytest.raises(PressureAbort, match="cornered"):
        while not bool(state.done):
            state, _, _ = rc.run_chunk(state, dispatch)
    assert rc.oom_fallbacks >= 1
    assert rc.report()["outbox_poisoned"]
    assert rc.abort_export_state() is not None


def test_oom_fallback_refuses_truncating_shrink():
    """Regression (r8 review 2): an OOM fallback whose lower rung can no
    longer hold the live events must corner into a loud PressureAbort —
    silently compact-truncating them would be exactly the loss the
    plane exists to prevent."""
    from shadow_tpu.ops.events import grow_queue

    model, hosts, stop, kw = _CASES["phold"]
    cfg, eng, state, params = _build(
        model, hosts, stop, qcap=4, pressure_abort=True, **kw
    )
    # simulate a prior escalation 4 -> 8 whose occupancy then rose past
    # the base rung: grow the slab and stuff it to 6 live events/host
    h = state.queue.t.shape[0]
    q = grow_queue(state.queue, 8)
    extra = [(
        jnp.ones((h,), bool), jnp.full((h,), 50 * MS, jnp.int64),
        pack_order(1, jnp.arange(h, dtype=jnp.int64), 7000 + i),
        jnp.full((h,), 3, jnp.int32), jnp.zeros((h, 4), jnp.int32),
    ) for i in range(3)]
    state = state._replace(queue=q_push_many(q, extra))
    assert int(np.asarray(jax.device_get(q_len(state.queue))).max()) > 4
    rc = ResilienceController(
        pressure=PressureOptions(policy="escalate", max_capacity=64),
    )
    rc._cap_ladder = [4, 8, 16, 32, 64]
    rc._box_ladder = [state.outbox.t.shape[1]]

    class XlaRuntimeError(RuntimeError):
        pass

    def dispatch(s, g, c, b):
        raise XlaRuntimeError("RESOURCE_EXHAUSTED: transient")

    with pytest.raises(PressureAbort, match="no longer fit"):
        rc.run_chunk(state, dispatch)
    assert rc.aborted
    # the pre-chunk snapshot (grown shape, events intact) still exports
    good = rc.abort_export_state()
    assert good is not None and good.queue.t.shape[1] == 8


def test_gears_and_escalate_compose():
    """Both axes through the one snapshot-replay loop: a gear ladder
    started at the bottom (forcing shed replays) composes with capacity
    escalation (forcing regrow replays) — the accepted result is still
    bit-identical to the full-width run launched at the final shape."""
    from shadow_tpu.core.gears import GearController, resolve_gear_ladder

    model, hosts, stop, kw = _CASES["phold"]
    cfg, eng, state, params = _build(
        model, hosts, stop, qcap=4, pressure_abort=True, **kw
    )
    ladder = resolve_gear_ladder("auto", cfg.sends_per_host_round)
    gearctl = GearController(ladder)
    gearctl.gear = ladder[0]  # bottom start forces real sheds
    rc = ResilienceController(
        gearctl=gearctl,
        pressure=PressureOptions(policy="escalate", max_capacity=256),
    )
    while not bool(state.done):
        state, _, _ = rc.run_chunk(
            state,
            lambda s, g, c, b: eng.run_chunk_resized(s, params, g, c, b),
        )
    assert gearctl.replays > 0 and rc.regrows + rc.proactive_regrows > 0
    assert int(np.asarray(jax.device_get(state.queue.dropped)).sum()) == 0
    cap_f = state.queue.t.shape[1]
    _, eng_r, ref, params_r = _build(model, hosts, stop, qcap=cap_f, **kw)
    eng_r.cfg = dataclasses.replace(
        eng_r.cfg, microstep_limit=cfg.effective_microstep_limit,
        max_round_inserts=cap_f,
    )
    eng_r._build_run_chunk()
    while not bool(ref.done):
        ref = eng_r.run_chunk(ref, params_r)
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(state.stats.digest)),
        np.asarray(jax.device_get(ref.stats.digest)),
    )


def test_escalate_would_have_dropped():
    """Evidence the gate is not vacuous: the same workload at the seed
    capacity under the default drop policy genuinely sheds."""
    model, hosts, stop, kw = _CASES["phold"]
    _, eng, state, params = _build(model, hosts, stop, qcap=4, **kw)
    while not bool(state.done):
        state = eng.run_chunk(state, params)
    assert int(np.asarray(jax.device_get(state.queue.dropped)).sum()) > 0


def test_escalate_grows_outbox_on_budget_pressure():
    """Send-budget drops are pressure too: a tiny outbox escalates to a
    wider one and the accepted run carries zero budget drops."""
    model, hosts, stop, kw = _CASES["phold"]
    kw = {**kw, "sends_budget": 1}
    _, state, rc = _run_escalated(model, hosts, stop, qcap=16, **kw)
    assert state.outbox.t.shape[1] > 1
    assert int(np.asarray(
        jax.device_get(state.stats.pkts_budget_dropped)
    ).sum()) == 0


def test_abort_policy_stops_at_first_drop():
    """`pressure: abort`: the run raises at the first dropping chunk and
    the export state is the honest record — drops visible, flagged."""
    model, hosts, stop, kw = _CASES["phold"]
    cfg, eng, state, params = _build(
        model, hosts, stop, qcap=4, pressure_abort=True, **kw
    )
    rc = ResilienceController(pressure=PressureOptions(policy="abort"))
    with pytest.raises(PressureAbort, match="first capacity drop"):
        while not bool(state.done):
            state, _, _ = rc.run_chunk(
                state,
                lambda s, g, c, b: eng.run_chunk_resized(s, params, g, c, b),
            )
    assert rc.aborted
    exported = rc.abort_export_state()
    assert exported is not None
    total = (
        int(np.asarray(jax.device_get(exported.queue.dropped)).sum())
        + int(np.asarray(
            jax.device_get(exported.stats.pkts_budget_dropped)
        ).sum())
    )
    assert total > 0  # the drop is IN the honest record


def test_oom_fallback_survives_and_corners_gracefully():
    """Forced-OOM degradation: the grown program's dispatch raising the
    RESOURCE_EXHAUSTED signature falls back one rung (process alive,
    counted), and with drops persisting and every higher rung poisoned
    the controller aborts via PressureAbort with the last good pre-chunk
    snapshot still exportable."""
    model, hosts, stop, kw = _CASES["phold"]
    cfg, eng, state, params = _build(
        model, hosts, stop, qcap=4, pressure_abort=True, **kw
    )
    rc = ResilienceController(
        pressure=PressureOptions(policy="escalate", max_capacity=64),
        queue_block=cfg.queue_block,
    )

    class XlaRuntimeError(RuntimeError):
        pass

    def dispatch(s, g, c, b):
        if c > 4:
            raise XlaRuntimeError(
                "RESOURCE_EXHAUSTED: Out of memory allocating grown slab"
            )
        return eng.run_chunk_resized(s, params, g, c, b)

    with pytest.raises(PressureAbort, match="cornered"):
        while not bool(state.done):
            state, _, _ = rc.run_chunk(state, dispatch)
    assert rc.oom_fallbacks >= 1
    assert rc.aborted
    rep = rc.report()
    assert rep["capacity_poisoned"]  # the OOM'd rungs are recorded
    good = rc.abort_export_state()
    assert good is not None
    # the exported prefix is clean: pre-chunk snapshots never hold drops
    assert int(np.asarray(jax.device_get(good.queue.dropped)).sum()) == 0


def test_drop_policy_traces_no_pressure_code():
    """The default policy is program-identical to the pre-pressure
    engine: no pressure lane in the carry, no abort condition traced."""
    from shadow_tpu.core.engine import EngineConfig, _init_stats

    cfg = EngineConfig(num_hosts=4, stop_time=1)
    assert cfg.pressure_abort is False
    assert _init_stats(cfg).pressure is None
    model, hosts, stop, kw = _CASES["phold"]
    _, eng, state, params = _build(model, hosts, stop, qcap=16, **kw)
    assert state.stats.pressure is None


def test_resolve_ladder():
    assert resolve_ladder(4, 64, 2) == [4, 8, 16, 32, 64]
    assert resolve_ladder(4, 60, 2) == [4, 8, 16, 32]
    assert resolve_ladder(8, 8, 2) == [8]
    assert resolve_ladder(3, 50, 4) == [3, 12, 48]


def test_pressure_options_parse():
    assert PressureOptions.from_dict(None).policy == "drop"
    assert not PressureOptions.from_dict(None).active
    p = PressureOptions.from_dict(
        {"policy": "escalate", "max_capacity": 128, "headroom": 0.5}
    )
    assert p.active and p.max_capacity == 128 and p.headroom == 0.5
    for bad in (
        {"policy": "grow"},
        {"max_capacity": -1},
        {"growth_factor": 1},
        {"headroom": 1.5},
        {"unknown": 1},
    ):
        with pytest.raises(ConfigError):
            PressureOptions.from_dict(bad)


def test_simulation_build_wiring_and_rejections():
    """Config-level wiring: policies set the engine static; unsupported
    combinations fail loudly at build."""
    from shadow_tpu.config.options import ConfigOptions
    from shadow_tpu.sim import Simulation

    def cfg_dict(**pressure):
        return {
            "general": {"stop_time": "1 s", "seed": 1},
            "network": {"graph": {"type": "1_gbit_switch"}},
            **({"pressure": pressure} if pressure else {}),
            "hosts": {
                "n": {"count": 4, "network_node_id": 0,
                      "processes": [{"model": "phold",
                                     "model_args": {"population": 1}}]},
            },
        }

    sim = Simulation(ConfigOptions.from_dict(cfg_dict()), world=1)
    assert sim.engine_cfg.pressure_abort is False
    sim = Simulation(
        ConfigOptions.from_dict(cfg_dict(policy="escalate")), world=1
    )
    assert sim.engine_cfg.pressure_abort is True
    # cpu-reference oracle cannot model the pressure plane
    d = cfg_dict(policy="abort")
    d["experimental"] = {"scheduler": "cpu-reference"}
    with pytest.raises(ConfigError, match="cpu-reference"):
        Simulation(ConfigOptions.from_dict(d), world=1)
    # merge_rows' positional shed is not capacity-curable
    d = cfg_dict(policy="escalate")
    d["experimental"] = {"merge_rows": 64}
    with pytest.raises(ConfigError, match="merge_rows"):
        Simulation(ConfigOptions.from_dict(d), world=1)
    # explicit a2a_block sheds are not capacity-curable either
    d = cfg_dict(policy="escalate")
    d["experimental"] = {"a2a_block": 64}
    with pytest.raises(ConfigError, match="a2a_block"):
        Simulation(ConfigOptions.from_dict(d), world=1)
    # ceilings below the configured shapes are config errors
    d = cfg_dict(policy="escalate", max_capacity=8)
    d["experimental"] = {"event_queue_capacity": 16}
    with pytest.raises(ConfigError, match="max_capacity"):
        Simulation(ConfigOptions.from_dict(d), world=1)


def test_hybrid_rejects_escalate():
    from shadow_tpu.config.options import ConfigOptions
    from shadow_tpu.cosim import HybridSimulation

    cfg = ConfigOptions.from_dict({
        "general": {"stop_time": "1 s", "seed": 1},
        "network": {"graph": {"type": "1_gbit_switch"}},
        "pressure": {"policy": "escalate"},
        "hosts": {
            "a": {"network_node_id": 0,
                  "processes": [{"path": "udp_echo_server",
                                 "args": ["port=9000"]}]},
        },
    })
    with pytest.raises(ConfigError, match="hybrid"):
        HybridSimulation(cfg, world=1)


def test_hybrid_abort_policy_clean_run():
    """The hybrid driver accepts the abort policy and a drop-free run
    completes normally, reporting the pressure block (the roomy hybrid
    slab never pressures here — the loud-stop path is gated at the
    engine level, same detector the modeled driver tests exercise)."""
    from shadow_tpu.config.options import ConfigOptions
    from shadow_tpu.cosim import HybridSimulation

    cfg = ConfigOptions.from_dict({
        "general": {"stop_time": "2 s", "seed": 4},
        "network": {"graph": {"type": "1_gbit_switch"}},
        "pressure": {"policy": "abort"},
        "hosts": {
            "server": {"network_node_id": 0,
                       "processes": [{"path": "udp_echo_server",
                                      "args": ["port=9000"]}]},
            "cli": {"network_node_id": 0,
                    "processes": [{"path": "udp_ping",
                                   "args": ["server=server", "port=9000",
                                            "count=2"],
                                   "expected_final_state": {"exited": 0}}]},
        },
    })
    sim = HybridSimulation(cfg, world=1)
    r = sim.run()
    assert r["process_failures"] == 0
    assert r["pressure"]["policy"] == "abort"
    assert "pressure_aborted" not in r
    assert sim.engine_cfg.pressure_abort is True


def test_campaign_rejects_pressure():
    from tools.campaign import build_campaign

    with pytest.raises(ConfigError, match="pressure"):
        build_campaign({
            "general": {"stop_time": "1 s", "seed": 1},
            "network": {"graph": {"type": "1_gbit_switch"}},
            "pressure": {"policy": "escalate"},
            "campaign": {"seeds": [1, 2]},
            "hosts": {
                "n": {"count": 2, "network_node_id": 0,
                      "processes": [{"model": "phold",
                                     "model_args": {"population": 1}}]},
            },
        })


# ---------------------------------------------------------------------------
# cross-capacity checkpoint restore
# ---------------------------------------------------------------------------


def _harness_sim(model, hosts, stop, rounds_per_chunk=16, **kw):
    """A minimal object with the attribute surface save/load_checkpoint
    need (state, engine_cfg, params, engine) — the engine-harness
    stand-in for a full Simulation."""
    cfg, m, params, mstate, events = build_sim(
        model, hosts, stop, rounds_per_chunk=rounds_per_chunk, **kw
    )
    eng = Engine(cfg, m)
    state, params = eng.init_state(params, mstate, events, seed=1)
    ns = types.SimpleNamespace(
        state=state, engine_cfg=cfg, params=params, engine=eng,
        cfg=types.SimpleNamespace(pressure=PressureOptions()),
    )
    return ns


_ROUNDTRIP_SCRIPT = """
import json, sys, types
import numpy as np
import jax
from shadow_tpu.core import Engine
from shadow_tpu.core.checkpoint import load_checkpoint, save_checkpoint
from shadow_tpu.config.options import PressureOptions
from tests.engine_harness import build_sim, mk_hosts

hosts = mk_hosts(8, {"mean_delay": "20 ms", "population": 3})
KW = dict(loss=0.1, microstep_limit=32, rounds_per_chunk=4)

def fresh(qcap):
    cfg, m, params, mstate, events = build_sim(
        "phold", hosts, 300_000_000, qcap=qcap, **KW
    )
    eng = Engine(cfg, m)
    state, params = eng.init_state(params, mstate, events, seed=1)
    return types.SimpleNamespace(
        state=state, engine_cfg=cfg, params=params, engine=eng,
        cfg=types.SimpleNamespace(pressure=PressureOptions()),
    )

def dig(st):
    return int(np.bitwise_xor.reduce(
        np.asarray(jax.device_get(st.stats.digest))
    ))

# one short chunk at C=16, checkpoint mid-run
a = fresh(16)
a.state = a.engine.run_chunk(a.state, a.params)
assert not bool(a.state.done)
now_saved = int(a.state.now)
path = save_checkpoint(sys.argv[1], a)

# resume into a sim built at C'=32: exact guard differs only in the
# migratable capacity shape -> migration path
b = fresh(32)
load_checkpoint(path, b)
resumed_cap = b.state.queue.t.shape[1]
resumed_now = int(b.state.now)
while not bool(b.state.done):
    b.state = b.engine.run_chunk(b.state, b.params)

digs = {}
drops = {}
for qcap in (16, 32):
    r = fresh(qcap)
    while not bool(r.state.done):
        r.state = r.engine.run_chunk(r.state, r.params)
    digs[qcap] = dig(r.state)
    drops[qcap] = int(np.asarray(jax.device_get(r.state.queue.dropped)).sum())
print(json.dumps({
    "resumed_cap": resumed_cap, "now_saved": now_saved,
    "resumed_now": resumed_now, "resumed_digest": dig(b.state),
    "digest_16": digs[16], "digest_32": digs[32],
    "drops_16": drops[16], "drops_32": drops[32],
}))
"""


def test_checkpoint_cross_capacity_roundtrip(tmp_path):
    """A checkpoint written at C resumes at C' > C through the migration
    ops, and the continued run is bit-identical to both an uninterrupted
    run at C and one at C' (the prefix was drop-free, the valve is
    pinned equal, so all three trajectories coincide). Subprocess-
    isolated: multiple compiled runs in one process are this box's
    heap-corruption magnet (tests/subproc.py)."""
    from tests.subproc import run_isolated_json

    r = run_isolated_json(_ROUNDTRIP_SCRIPT, str(tmp_path / "ck"))
    assert r["resumed_cap"] == 32
    assert r["resumed_now"] == r["now_saved"]
    assert r["drops_16"] == 0 and r["drops_32"] == 0
    assert r["digest_16"] == r["digest_32"] == r["resumed_digest"]


def test_checkpoint_shrink_refuses_when_overfull(tmp_path):
    """Refusal only when migration is impossible: resuming into a
    capacity the checkpoint's live events cannot fit raises loudly."""
    from shadow_tpu.core.checkpoint import (
        CheckpointError, load_checkpoint, save_checkpoint,
    )

    model, hosts, stop, kw = _CASES["phold"]
    kw = dict(kw, qcap=16, microstep_limit=32, rounds_per_chunk=4)
    a = _harness_sim(model, hosts, stop, **kw)
    a.state = a.engine.run_chunk(a.state, a.params)
    assert not bool(a.state.done)
    # stuff the queue past the target capacity (state content is not in
    # the guard, so the checkpoint remains loadable-in-principle)
    h = a.state.queue.t.shape[0]
    extra = [(
        jnp.ones((h,), bool), jnp.full((h,), 250 * MS, jnp.int64),
        pack_order(1, jnp.arange(h, dtype=jnp.int64), 5000 + i),
        jnp.full((h,), 3, jnp.int32), jnp.zeros((h, 4), jnp.int32),
    ) for i in range(8)]
    a.state = a.state._replace(queue=q_push_many(a.state.queue, extra))
    occ = int(np.asarray(jax.device_get(q_len(a.state.queue))).max())
    assert occ > 8
    path = save_checkpoint(str(tmp_path / "ck"), a)
    b = _harness_sim(model, hosts, stop, **{**kw, "qcap": 8})
    with pytest.raises(CheckpointError, match="cannot resume"):
        load_checkpoint(path, b)


_ESCALATED_CKPT_SCRIPT = """
import dataclasses, json, sys, types
from shadow_tpu.core import Engine
from shadow_tpu.core.checkpoint import load_checkpoint, save_checkpoint
from shadow_tpu.core.pressure import ResilienceController
from shadow_tpu.config.options import PressureOptions
from tests.engine_harness import build_sim, mk_hosts

hosts = mk_hosts(8, {"mean_delay": "20 ms", "population": 3})
KW = dict(loss=0.1, qcap=4, microstep_limit=16, rounds_per_chunk=4)
PRESS = PressureOptions(policy="escalate", max_capacity=64)

def fresh():
    cfg, m, params, mstate, events = build_sim(
        "phold", hosts, 300_000_000, **KW
    )
    cfg = dataclasses.replace(cfg, pressure_abort=True)
    eng = Engine(cfg, m)
    state, params = eng.init_state(params, mstate, events, seed=1)
    return types.SimpleNamespace(
        state=state, engine_cfg=cfg, params=params, engine=eng,
        cfg=types.SimpleNamespace(pressure=PRESS),
    )

a = fresh()
rc = ResilienceController(pressure=PRESS)
for _ in range(3):
    if bool(a.state.done):
        break
    a.state, _, _ = rc.run_chunk(
        a.state,
        lambda s, g, c, b: a.engine.run_chunk_resized(s, a.params, g, c, b),
    )
grown = a.state.queue.t.shape[1]
now_saved = int(a.state.now)
path = save_checkpoint(sys.argv[1], a)

# same config, escalate policy target: keeps the grown shape
b = fresh()
load_checkpoint(path, b)
print(json.dumps({
    "grown": grown, "now_saved": now_saved,
    "resumed_cap": b.state.queue.t.shape[1],
    "resumed_now": int(b.state.now),
}))
"""


def test_checkpoint_escalated_state_resumes(tmp_path):
    """A checkpoint written MID-ESCALATION (state regrown past the
    configured base) restores, and under an escalate target it keeps the
    grown shape. Subprocess-isolated (heap-corruption magnet, see the
    round-trip test)."""
    from tests.subproc import run_isolated_json

    r = run_isolated_json(_ESCALATED_CKPT_SCRIPT, str(tmp_path / "ck"))
    assert r["grown"] > 4  # the escalation genuinely regrew pre-save
    assert r["resumed_cap"] == r["grown"]
    assert r["resumed_now"] == r["now_saved"]


# ---------------------------------------------------------------------------
# full-driver end-to-end (subprocess-isolated: compiled Simulation runs
# intermittently heap-corrupt in-process on this box — CHANGES.md)
# ---------------------------------------------------------------------------

_DRIVER_SCRIPT = """
import io, json, sys
from shadow_tpu.config.options import ConfigOptions
from shadow_tpu.sim import Simulation

def cfg(policy, qcap):
    return ConfigOptions.from_dict({
        "general": {"stop_time": "2 s", "seed": 7,
                    "heartbeat_interval": "500 ms"},
        "network": {"graph": {"type": "1_gbit_switch"}},
        "experimental": {"event_queue_capacity": qcap,
                         "sends_per_host_round": 4,
                         "rounds_per_chunk": 16},
        **({"pressure": {"policy": policy, "max_capacity": 64}}
           if policy else {}),
        "hosts": {
            "n": {"count": 16, "network_node_id": 0,
                  "processes": [{"model": "phold",
                                 "model_args": {"population": 6,
                                                "mean_delay": "100 ms"}}]},
        },
    })

mode = sys.argv[1]
log = io.StringIO()
sim = Simulation(cfg("escalate" if mode == "esc" else None, 8), world=1)
rep = sim.run(log=log)
print(json.dumps({mode: rep, "log": log.getvalue()}))
"""


def test_simulation_driver_escalates_end_to_end():
    """The Simulation driver wiring, end to end: an escalate run over an
    undersized queue finishes drop-free with the pressure block + flat
    counters in sim-stats and cap= on the heartbeat line, while the
    default-policy twin genuinely sheds. One subprocess per leg (each
    compiled Simulation run is its own corruption-isolation domain)."""
    from tests.subproc import run_isolated_json

    esc_rep = run_isolated_json(_DRIVER_SCRIPT, "esc")
    drop_rep = run_isolated_json(_DRIVER_SCRIPT, "drop")
    esc, drop = esc_rep["esc"], drop_rep["drop"]
    reps = {"log": esc_rep["log"]}
    assert drop["queue_overflow_dropped"] > 0  # the gate is not vacuous
    assert esc["queue_overflow_dropped"] == 0
    assert esc["packets_budget_dropped"] == 0
    p = esc["pressure"]
    assert p["policy"] == "escalate"
    assert esc["pressure_regrows"] > 0
    assert p["capacity"] > p["base_capacity"]
    assert "pressure_aborted" not in esc
    # heartbeat carries the ACTIVE capacity on pressure runs
    assert "cap=" in reps["log"]


# ---------------------------------------------------------------------------
# heartbeat cap= + parser compatibility
# ---------------------------------------------------------------------------


def test_heartbeat_cap_field_parses(tmp_path):
    from shadow_tpu.sim import heartbeat_line
    from tools.parse_shadow import parse_heartbeats

    new = heartbeat_line(
        2_000_000_000, 3.0, 99, 80, 40, 4096, 7, gear=4, cap=32
    )
    old = heartbeat_line(2_000_000_000, 3.0, 99, 80, 40, 4096, 7)
    log = tmp_path / "run.log"
    log.write_text(new + "\n" + old + "\n")
    rows = parse_heartbeats(str(log), strict=True)
    assert len(rows) == 2
    assert rows[0]["cap"] == 32 and rows[0]["gear"] == 4
    assert "cap" not in rows[1]
