import jax.numpy as jnp
import numpy as np

from shadow_tpu.ops import rng_init, rng_next_u64, rng_uniform

H = 8


def test_lanes_distinct_and_deterministic():
    s1 = rng_init(H, seed=42)
    s2 = rng_init(H, seed=42)
    assert np.array_equal(np.asarray(s1.s), np.asarray(s2.s))
    s3 = rng_init(H, seed=43)
    assert not np.array_equal(np.asarray(s1.s), np.asarray(s3.s))
    # lanes differ between hosts
    assert len({int(x) for x in np.asarray(s1.s[:, 0])}) == H


def test_masked_advance_is_per_host():
    """A host's draw sequence must not depend on other hosts' draws — the
    property the determinism gate relies on (SURVEY.md §5.2)."""
    mask_all = jnp.ones((H,), bool)
    mask_half = jnp.arange(H) < H // 2

    s = rng_init(H, seed=7)
    s_a, _ = rng_next_u64(s, mask_half)  # only first half advances
    s_a, draw_a = rng_next_u64(s_a, mask_all)

    s_b, draw_b = rng_next_u64(s, mask_all)  # second half's first real draw

    # hosts in the second half see the same first draw either way
    assert np.array_equal(np.asarray(draw_a[H // 2 :]), np.asarray(draw_b[H // 2 :]))
    # hosts in the first half see their *second* draw in sequence a
    s_c, _ = rng_next_u64(s, mask_all)
    _, draw_c = rng_next_u64(s_c, mask_all)
    assert np.array_equal(np.asarray(draw_a[: H // 2]), np.asarray(draw_c[: H // 2]))


def test_uniform_in_range():
    s = rng_init(H, seed=1)
    mask = jnp.ones((H,), bool)
    for _ in range(16):
        s, u = rng_uniform(s, mask)
        u = np.asarray(u)
        assert (u >= 0).all() and (u < 1).all()
