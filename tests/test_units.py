from shadow_tpu.config.units import (
    TimeUnit,
    parse_bits_per_sec,
    parse_bytes,
    parse_time_ns,
)

import pytest


def test_time_suffixes():
    assert parse_time_ns("50 ms") == 50_000_000
    assert parse_time_ns("10s") == 10_000_000_000
    assert parse_time_ns("1 us") == 1_000
    assert parse_time_ns("3 min") == 180_000_000_000
    assert parse_time_ns("2 h") == 7_200_000_000_000
    assert parse_time_ns("1.5 ms") == 1_500_000


def test_time_bare_default_unit():
    assert parse_time_ns(10) == 10_000_000_000
    assert parse_time_ns("10") == 10_000_000_000
    assert parse_time_ns(10, TimeUnit.MS) == 10_000_000


def test_bitrates():
    assert parse_bits_per_sec("10 Mbit") == 10_000_000
    assert parse_bits_per_sec("81920 Kibit") == 81920 * 1024
    assert parse_bits_per_sec("1 Gbit") == 1_000_000_000
    assert parse_bits_per_sec(12345) == 12345


def test_bytes():
    assert parse_bytes("1 GiB") == 2**30
    assert parse_bytes("512 KB") == 512_000
    assert parse_bytes("100 B") == 100
    assert parse_bytes("2 MiB") == 2 * 2**20


def test_fractional_rounds_not_truncates():
    assert parse_time_ns("4.1 s") == 4_100_000_000
    assert parse_bits_per_sec("0.5 Mbit") == 500_000


def test_bad_units():
    with pytest.raises(ValueError):
        parse_time_ns("10 parsecs")
    with pytest.raises(ValueError):
        parse_bits_per_sec("10 Xbit")
