"""Fluid traffic plane (`fluid:` config block, shadow_tpu/net/fluid.py).

Gates, mirroring the ISSUE acceptance:
  - exactness: fluid ABSENT vs PRESENT-at-zero-demand (a class window
    that never activates) is bit-identical in digests, per-host event
    counts, and every drop counter, across echo/phold/tgen x
    flat/bucketed x K{1,4}; the world=8 legs run subprocess-isolated
    (tests/subproc.py, this box's documented corruption posture);
  - statistical gate: fluid PRESENT with demand is same-seed
    deterministic across reruns AND mesh shapes (world 1 == world 8
    digests/byte counters), sub-threshold background leaves the
    foreground bit-identical, and modest congestion keeps foreground
    FCT p50/p99 within the stated tolerance (50%) of the fluid-off
    calibration run;
  - background accounting: delivered + dropped bytes never exceed the
    offered integral, drops appear exactly under overload, and the
    coupling's loss mode lands in pkts_lost (counted, deterministic);
  - the fluid lanes ride the registries: memory-formula bytes == live
    carry bytes on a fluid-active state, checkpoint flatten/restore
    round-trips the lanes, heartbeat bg= round-trips parse_shadow
    --strict, options/engine validation is loud, and
    examples/fluid.yaml parses."""

from __future__ import annotations

import json
import os

import jax
import numpy as np
import pytest

from shadow_tpu.core import Engine
from tests.engine_harness import build_sim, mk_hosts

_REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))

# a class whose window opens far past every case's horizon: the fluid
# plane is TRACED IN (the gated program) but demand is zero for the
# whole run — the exactness matrix's "present at zero demand" point
ZERO_FLUID = {
    "link_capacity": "1 Gbit",
    "latency_factor_max": 1.5,
    "loss_max": 0.2,
    "classes": [{"src_zone": 0, "dst_zone": 0, "rate": "100 Mbit",
                 "start": "1000 s"}],
}

# modest always-on congestion: demand 2x the link capacity from t=0,
# latency-only coupling — the calibration scenario's background
CONGESTED_FLUID = {
    "link_capacity": "50 Mbit",
    "latency_factor_max": 1.2,
    "util_threshold": 0.5,
    "classes": [{"src_zone": 0, "dst_zone": 0, "rate": "100 Mbit",
                 "start": 0}],
}

_CASES = {
    "phold": ("phold", mk_hosts(8, {"mean_delay": "20 ms", "population": 3}),
              300_000_000, dict(loss=0.1)),
    "echo": ("udp_echo",
             [dict(host_id=0, name="server", start_time=0,
                   model_args={"role": "server"})]
             + [dict(host_id=i, name=f"c{i}", start_time=0,
                     model_args={"role": "client", "peer": "server",
                                 "interval": "4 ms", "size_bytes": 2000})
                for i in range(1, 5)],
             200_000_000, dict(bw_bits=2_000_000, loss=0.05)),
    "tgen": ("tgen_tcp",
             mk_hosts(5, {"flow_segs": 8, "flows": 2, "cwnd_cap": 8,
                          "rto_min": "100 ms"}),
             2_000_000_000,
             dict(loss=0.05, latency=10_000_000, sends_budget=16)),
}


def _run(model, hosts, stop, *, k=1, qb=0, fluid=None, seed=1, world=1,
         **kw):
    cfg, m, params, mstate, events = build_sim(
        model, hosts, stop, world=world, queue_block=qb,
        microstep_events=k, fluid=fluid, seed=seed, **kw
    )
    mesh = None
    if world > 1:
        mesh = jax.sharding.Mesh(np.array(jax.devices()[:world]), ("hosts",))
    eng = Engine(cfg, m, mesh)
    state, params = eng.init_state(params, mstate, events, seed=seed)
    chunks = 0
    while not bool(state.done):
        state = eng.run_chunk(state, params)
        chunks += 1
        assert chunks < 500
    return state


def _matrix_params():
    """The world-1 exactness matrix, tier-1-budgeted like test_netobs:
    the aligned (flat, k1)/(bucketed, k4) pairs run in tier-1, the
    mixed-axis combos (which add no code path the aligned pairs miss)
    carry the `slow` mark and run under `pytest -m ''`."""
    out = []
    for case in sorted(_CASES):
        for k in (1, 4):
            for qb in (0, 8):
                aligned = (k == 1) == (qb == 0)
                marks = () if aligned else (pytest.mark.slow,)
                out.append(pytest.param(
                    case, k, qb,
                    id=f"{case}-{'flat' if qb == 0 else 'bucketed'}-k{k}",
                    marks=marks,
                ))
    return out


@pytest.mark.parametrize("case,k,qb", _matrix_params())
def test_fluid_zero_demand_is_bit_identical(case, k, qb):
    """The exactness gate, world=1: fluid absent vs present-at-zero-
    demand across the model x layout x K matrix. The gated program is
    DIFFERENT (the tgen_fluid fingerprint pins it) but every value it
    produces is identical — zero background load yields loss 0.0 and
    latency multiplier exactly 1.0x, and the loss draw is a pure hash
    that never touches the RNG lanes."""
    model, hosts, stop, kw = _CASES[case]
    s_off = _run(model, hosts, stop, k=k, qb=qb, **kw)
    s_on = _run(model, hosts, stop, k=k, qb=qb, fluid=ZERO_FLUID, **kw)
    off, on = jax.device_get(s_off.stats), jax.device_get(s_on.stats)

    np.testing.assert_array_equal(np.asarray(off.digest),
                                  np.asarray(on.digest))
    np.testing.assert_array_equal(np.asarray(off.events),
                                  np.asarray(on.events))
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(s_off.queue.dropped)),
        np.asarray(jax.device_get(s_on.queue.dropped)),
    )
    for field in ("pkts_sent", "pkts_lost", "pkts_codel_dropped",
                  "pkts_budget_dropped", "pkts_delivered",
                  "pkts_unreachable", "monotonic_violations"):
        np.testing.assert_array_equal(
            np.asarray(getattr(off, field)), np.asarray(getattr(on, field)),
            err_msg=field,
        )
    # the ungated program carries NO fluid lanes; the gated one saw no
    # background (the window never opened)
    assert off.fl_bg_bytes is None and s_off.fluid is None
    assert int(np.asarray(on.fl_bg_bytes)) == 0
    assert int(np.asarray(on.fl_bg_dropped)) == 0
    assert (np.asarray(jax.device_get(s_on.fluid.rates)) == 0.0).all()


def test_fluid_demand_is_deterministic_across_reruns():
    """fluid PRESENT with demand: same seed => bit-identical digests
    and byte counters across reruns (the ODE is pure f64 math, the loss
    draw a pure hash)."""
    model, hosts, stop, kw = _CASES["phold"]
    fl = dict(CONGESTED_FLUID, loss_max=0.3)
    a = _run(model, hosts, stop, fluid=fl, **kw)
    b = _run(model, hosts, stop, fluid=fl, **kw)
    sa, sb = jax.device_get(a.stats), jax.device_get(b.stats)
    np.testing.assert_array_equal(np.asarray(sa.digest),
                                  np.asarray(sb.digest))
    assert int(np.asarray(sa.fl_bg_bytes)) == int(np.asarray(sb.fl_bg_bytes))
    assert int(np.asarray(sa.fl_bg_dropped)) == int(
        np.asarray(sb.fl_bg_dropped)
    )
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(a.fluid.rates)),
        np.asarray(jax.device_get(b.fluid.rates)),
    )
    # overload (demand 2x capacity, charged to both ends of the
    # self-zone) must clip: drops counted, never silent — and loss
    # coupling lands in pkts_lost
    assert int(np.asarray(sa.fl_bg_dropped)) > 0
    assert int(np.asarray(sa.fl_bg_bytes)) > 0
    assert int(np.asarray(sa.pkts_lost).sum()) > 0


def test_fluid_background_accounting_bounds():
    """delivered + dropped can never exceed the offered integral
    (demand x active time), and the per-round floor rounding loses at
    most rounds x 2 bytes of the accounting."""
    model, hosts, stop, kw = _CASES["phold"]
    st = _run(model, hosts, stop, fluid=CONGESTED_FLUID, **kw)
    s = jax.device_get(st.stats)
    delivered = int(np.asarray(s.fl_bg_bytes))
    dropped = int(np.asarray(s.fl_bg_dropped))
    # offered bound: 100 Mbit/s for the whole 0.3 s horizon
    offered = int(100e6 / 8 * 0.3)
    assert 0 < delivered + dropped <= offered
    # congestion means real clipping, not rounding dust
    assert dropped > delivered // 10


def test_fluid_subthreshold_background_is_inert():
    """Background riding BELOW the coupling threshold inflates nothing:
    the foreground is bit-identical to fluid-off while the background
    bytes still flow — the conservative-coupling contract's low-load
    corner."""
    model, hosts, stop, kw = _CASES["tgen"]
    fl = {
        # tiny demand against a huge link: util stays far below the
        # 0.7 default threshold, so over == 0 on every host
        "link_capacity": "10 Gbit",
        "latency_factor_max": 2.0,
        "loss_max": 0.5,
        "classes": [{"src_zone": 0, "dst_zone": 0, "rate": "1 Mbit",
                     "start": 0}],
    }
    s_off = _run(model, hosts, stop, **kw)
    s_on = _run(model, hosts, stop, fluid=fl, **kw)
    off, on = jax.device_get(s_off.stats), jax.device_get(s_on.stats)
    np.testing.assert_array_equal(np.asarray(off.digest),
                                  np.asarray(on.digest))
    np.testing.assert_array_equal(np.asarray(off.pkts_lost),
                                  np.asarray(on.pkts_lost))
    assert int(np.asarray(on.fl_bg_bytes)) > 0  # the background flowed


def _fct_ms(state):
    from shadow_tpu.obs.netobs import FlowCollector

    col = FlowCollector(64)
    col.drain(state.flows)
    fct = col.fct_ns()
    assert fct.size > 0, "calibration run completed no flows"
    return (
        float(np.percentile(fct, 50)) / 1e6,
        float(np.percentile(fct, 99)) / 1e6,
    )


# the documented tolerance of the calibration gate: modest congestion
# (latency coupling capped at 1.2x) may move foreground FCT by at most
# this relative fraction against the fluid-off run
FCT_TOLERANCE = 0.5


def test_fluid_foreground_fct_within_tolerance():
    """The statistical gate: on the tgen calibration scenario, modest
    background congestion (latency-only coupling, 1.2x cap) keeps the
    foreground FCT p50/p99 within FCT_TOLERANCE of the fluid-off run —
    the 'foreground statistically indistinguishable' claim with its
    tolerance stated instead of hoped."""
    model, hosts, stop, kw = _CASES["tgen"]
    kw = dict(kw, netobs=True, flow_records=64)
    s_off = _run(model, hosts, stop, **kw)
    s_on = _run(model, hosts, stop, fluid=CONGESTED_FLUID, **kw)
    p50_off, p99_off = _fct_ms(s_off)
    p50_on, p99_on = _fct_ms(s_on)
    for q, off_v, on_v in (("p50", p50_off, p50_on),
                           ("p99", p99_off, p99_on)):
        rel = abs(on_v - off_v) / off_v
        assert rel <= FCT_TOLERANCE, (
            f"fct {q}: fluid-off {off_v:.2f} ms vs fluid-on {on_v:.2f} ms "
            f"({rel * 100:.0f}% > {FCT_TOLERANCE * 100:.0f}% tolerance)"
        )
    # latency-only coupling never drops foreground packets
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(s_off.stats.pkts_lost)),
        np.asarray(jax.device_get(s_on.stats.pkts_lost)),
    )


# ---------------------------------------------------------------------------
# world=8 legs (subprocess-isolated, tests/subproc.py posture)
# ---------------------------------------------------------------------------

_W8_SCRIPT = """
import json, sys
import numpy as np
import jax
from shadow_tpu.core import Engine
from tests.engine_harness import build_sim, mk_hosts

model, qb, k = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
cases = {
    "phold": ("phold", mk_hosts(8, {"mean_delay": "20 ms", "population": 3}),
              300_000_000, dict(loss=0.1)),
    "udp_echo": ("udp_echo",
        [dict(host_id=0, name="server", start_time=0,
              model_args={"role": "server"})]
        + [dict(host_id=i, name=f"c{i}", start_time=0,
                model_args={"role": "client", "peer": "server",
                            "interval": "4 ms", "size_bytes": 2000})
           for i in range(1, 8)],
        200_000_000, dict(bw_bits=2_000_000, loss=0.05)),
    "tgen_tcp": ("tgen_tcp",
        mk_hosts(8, {"flow_segs": 8, "flows": 1, "cwnd_cap": 8,
                     "rto_min": "100 ms"}),
        1_500_000_000, dict(loss=0.05, latency=10_000_000, sends_budget=16)),
}
name, hosts, stop, kw = cases[model]
ZERO = {"link_capacity": "1 Gbit", "loss_max": 0.2,
        "classes": [{"src_zone": 0, "dst_zone": 0, "rate": "100 Mbit",
                     "start": "1000 s"}]}
DEMAND = {"link_capacity": "50 Mbit", "latency_factor_max": 1.2,
          "util_threshold": 0.5,
          "classes": [{"src_zone": 0, "dst_zone": 0, "rate": "100 Mbit",
                       "start": 0}]}

def run(world, fluid):
    cfg, m, params, mstate, events = build_sim(
        name, hosts, stop, world=world, queue_block=qb,
        microstep_events=k, fluid=fluid, **kw)
    mesh = None
    if world > 1:
        mesh = jax.sharding.Mesh(np.array(jax.devices()[:world]), ("hosts",))
    eng = Engine(cfg, m, mesh)
    state, params = eng.init_state(params, mstate, events, seed=1)
    chunks = 0
    while not bool(state.done):
        state = eng.run_chunk(state, params)
        chunks += 1
        assert chunks < 500
    return state

s_off = run(8, None)
s_zero = run(8, ZERO)
s_d1 = run(1, DEMAND)
s_d8 = run(8, DEMAND)
off, zero = jax.device_get(s_off.stats), jax.device_get(s_zero.stats)
d1, d8 = jax.device_get(s_d1.stats), jax.device_get(s_d8.stats)
out = {
    "zero_digest_equal": bool(
        (np.asarray(off.digest) == np.asarray(zero.digest)).all()),
    "zero_events_equal": bool(
        (np.asarray(off.events) == np.asarray(zero.events)).all()),
    "zero_dropped_equal": bool((
        np.asarray(jax.device_get(s_off.queue.dropped))
        == np.asarray(jax.device_get(s_zero.queue.dropped))).all()),
    "zero_bg": int(np.asarray(zero.fl_bg_bytes)),
    "mesh_digest_equal": bool(
        (np.asarray(d1.digest) == np.asarray(d8.digest)).all()),
    "mesh_bg_equal": (int(np.asarray(d1.fl_bg_bytes))
                      == int(np.asarray(d8.fl_bg_bytes))),
    "mesh_drop_equal": (int(np.asarray(d1.fl_bg_dropped))
                        == int(np.asarray(d8.fl_bg_dropped))),
    "bg_bytes": int(np.asarray(d8.fl_bg_bytes)),
}
print(json.dumps(out))
"""


@pytest.mark.parametrize(
    "model,qb,k",
    [("udp_echo", 0, 1), ("phold", 8, 1), ("tgen_tcp", 0, 4)],
    ids=["echo-flat-k1", "phold-bucketed-k1", "tgen-flat-k4"],
)
def test_fluid_world8_exactness_and_mesh_invariance(model, qb, k):
    """World-8 legs: zero-demand exactness at world 8, plus the
    mesh-shape gate — demand runs at world 1 and world 8 produce
    bit-identical digests and background byte counters (the ODE is
    replicated math over psum'd integer folds)."""
    from tests.subproc import run_isolated_json

    out = run_isolated_json(_W8_SCRIPT, model, qb, k)
    assert out["zero_digest_equal"], "zero-demand fluid changed digests"
    assert out["zero_events_equal"] and out["zero_dropped_equal"]
    assert out["zero_bg"] == 0
    assert out["mesh_digest_equal"], "digests varied with mesh shape"
    assert out["mesh_bg_equal"] and out["mesh_drop_equal"]
    assert out["bg_bytes"] > 0


# ---------------------------------------------------------------------------
# registries: memory formula, checkpoint round-trip
# ---------------------------------------------------------------------------


def test_fluid_memory_formula_equals_carry_bytes():
    """The HBM byte model prices the fluid planes: formula bytes ==
    live carry leaf bytes, exactly (the test_memory single-source gate
    extended to a fluid-active state)."""
    import shadow_tpu.obs.memory as M

    model, hosts, stop, kw = _CASES["phold"]
    cfg, m, params, mstate, events = build_sim(
        model, hosts, stop, fluid=CONGESTED_FLUID, **kw
    )
    eng = Engine(cfg, m, None)
    state, params = eng.init_state(params, mstate, events, seed=1)

    def leaf_at(st, path):
        obj = st
        for part in path.split("."):
            obj = getattr(obj, part)
        return obj

    for dims in (M.dims_of_config(cfg), M.dims_of_state(cfg, state)):
        comps = M.registered_component_bytes(dims)
        seen = set()
        for comp, paths in comps.items():
            for path, want in paths.items():
                leaf = leaf_at(state, path)
                assert M.leaf_nbytes(leaf) == want, (
                    f"{path}: formula {want} != leaf "
                    f"{M.leaf_nbytes(leaf)}"
                )
                seen.add(path)
        assert {"fluid.rates", "fluid.link_util", "stats.fl_bg_bytes",
                "stats.fl_bg_dropped"} <= seen
    # and the fluid-off dims carry NO fluid planes
    cfg_off, *_ = build_sim(model, hosts, stop, **kw)
    comps_off = M.registered_component_bytes(M.dims_of_config(cfg_off))
    flat = {p for paths in comps_off.values() for p in paths}
    assert not any(p.startswith("fluid.") for p in flat)


def test_fluid_checkpoint_roundtrip_continues_identically():
    """Checkpoint save/restore extends naturally: a mid-run flatten +
    restore of a fluid-active state (the .npz leaf path) continues to
    the same digests and background counters as the uninterrupted
    run."""
    from shadow_tpu.core.checkpoint import _dump_leaves, _restore_leaves

    model, hosts, stop, kw = _CASES["phold"]
    cfg, m, params, mstate, events = build_sim(
        model, hosts, stop, fluid=CONGESTED_FLUID,
        rounds_per_chunk=16, **kw
    )
    eng = Engine(cfg, m, None)
    state, params = eng.init_state(params, mstate, events, seed=1)
    state = eng.run_chunk(state, params)  # mid-run point

    arrays, _ = _dump_leaves(state)
    # a fresh same-config build provides the shape/dtype template
    cfg2, m2, params2, mstate2, events2 = build_sim(
        model, hosts, stop, fluid=CONGESTED_FLUID,
        rounds_per_chunk=16, **kw
    )
    eng2 = Engine(cfg2, m2, None)
    fresh, params2 = eng2.init_state(params2, mstate2, events2, seed=1)
    restored = _restore_leaves(arrays, fresh, None)

    def drive(e, st, p):
        chunks = 0
        while not bool(st.done):
            st = e.run_chunk(st, p)
            chunks += 1
            assert chunks < 500
        return st

    a = drive(eng, state, params)
    b = drive(eng2, restored, params2)
    sa, sb = jax.device_get(a.stats), jax.device_get(b.stats)
    np.testing.assert_array_equal(np.asarray(sa.digest),
                                  np.asarray(sb.digest))
    assert int(np.asarray(sa.fl_bg_bytes)) == int(np.asarray(sb.fl_bg_bytes))
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(a.fluid.link_util)),
        np.asarray(jax.device_get(b.fluid.link_util)),
    )


# ---------------------------------------------------------------------------
# options / engine validation, example yaml, report helpers
# ---------------------------------------------------------------------------


def test_fluid_options_parse_and_validate():
    from shadow_tpu.config.options import ConfigError, FluidOptions

    f = FluidOptions.from_dict({
        "link_capacity": "2 Gbit", "tau": "20 ms", "util_threshold": 0.6,
        "loss_max": 0.1, "latency_factor_max": 1.5, "seed": 9,
        "classes": [{"name": "crowd", "src_zone": 1, "dst_zone": 0,
                     "rate": "500 Mbit", "start": "5 s", "end": "15 s"}],
    })
    assert f.active and len(f.classes) == 1
    assert f.link_capacity == 2_000_000_000
    assert f.classes[0].rate == 500_000_000
    assert f.classes[0].start == 5_000_000_000

    assert not FluidOptions.from_dict(None).active
    assert not FluidOptions.from_dict({}).active

    with pytest.raises(ConfigError):
        FluidOptions.from_dict({"latency_factor_max": 0.5})
    with pytest.raises(ConfigError):
        FluidOptions.from_dict({"loss_max": 1.5})
    with pytest.raises(ConfigError):
        FluidOptions.from_dict({"util_threshold": 1.0})
    with pytest.raises(ConfigError):
        FluidOptions.from_dict({"classes": [{"rate": "0 bit"}]})
    with pytest.raises(ConfigError):
        FluidOptions.from_dict({"classes": [{}]})  # rate required
    with pytest.raises(ConfigError):
        FluidOptions.from_dict({"unknown_knob": 1})
    with pytest.raises(ConfigError):
        FluidOptions.from_dict({
            "classes": [{"rate": "1 Mbit", "start": "2 s", "end": "1 s"}],
        })


def test_compile_fluid_validates_zones_and_windows():
    from shadow_tpu.config.options import FluidOptions
    from shadow_tpu.net.fluid import compile_fluid

    opts = FluidOptions.from_dict({
        "classes": [{"src_zone": 3, "dst_zone": 0, "rate": "1 Mbit"}],
    })
    with pytest.raises(ValueError):
        compile_fluid(opts, num_links=2)
    sched = compile_fluid(opts, num_links=4)
    assert sched.active and sched.classes == 1 and sched.links == 4
    # end omitted = open-ended (never closes inside any horizon)
    assert int(np.asarray(sched.params.win_end)[0]) > 10**12
    # inert block: no params, not active
    empty = compile_fluid(FluidOptions.from_dict(None), num_links=4)
    assert not empty.active and empty.params is None


def test_engine_config_validates_fluid_statics():
    from shadow_tpu.core.engine import EngineConfig

    with pytest.raises(ValueError):
        EngineConfig(num_hosts=4, stop_time=10**9, fluid_classes=1)
    with pytest.raises(ValueError):
        EngineConfig(num_hosts=4, stop_time=10**9, fluid_classes=1,
                     fluid_links=1, fluid_lat_max_x1000=500)
    with pytest.raises(ValueError):
        EngineConfig(num_hosts=4, stop_time=10**9, fluid_classes=1,
                     fluid_links=1, fluid_loss_max=1.5)
    cfg = EngineConfig(num_hosts=4, stop_time=10**9, fluid_classes=2,
                       fluid_links=3)
    assert cfg.fluid_active


def test_engine_requires_matching_fluid_params():
    """init_state refuses a config/params fluid mismatch loudly (the
    faults-plane contract)."""
    model, hosts, stop, kw = _CASES["phold"]
    cfg, m, params, mstate, events = build_sim(
        model, hosts, stop, fluid=CONGESTED_FLUID, **kw
    )
    eng = Engine(cfg, m, None)
    with pytest.raises(ValueError, match="EngineParams.fluid"):
        eng.init_state(params._replace(fluid=None), mstate, events, seed=1)


def test_example_fluid_yaml_parses():
    from shadow_tpu.config.options import load_config

    cfg = load_config(os.path.join(_REPO, "examples", "fluid.yaml"))
    assert cfg.fluid.active and len(cfg.fluid.classes) == 3
    assert cfg.fluid.latency_factor_max == 1.5
    assert cfg.fluid.loss_max == 0.0
    assert cfg.observability.network


def test_cosim_rejects_fluid():
    """The hybrid (managed-process) driver rejects the fluid plane
    loudly — the CPU plane's packets would bypass the coupling."""
    from shadow_tpu.config.options import ConfigError, ConfigOptions
    from shadow_tpu.cosim import HybridSimulation

    cfg = ConfigOptions.from_dict({
        "general": {"stop_time": "1 s"},
        "fluid": {"classes": [{"rate": "1 Mbit"}]},
        "hosts": {"a": {"processes": [{"path": "udp_echo_server"}]}},
    })
    with pytest.raises(ConfigError, match="fluid"):
        HybridSimulation(cfg)


def test_fluid_report_helpers():
    from shadow_tpu.net.fluid import (
        background_share_sentence, bench_fluid_block,
    )

    rep = {"classes": 2, "links": 4, "bg_bytes": 900, "bg_dropped": 100,
           "delivered_share": 0.9, "link_util_final": [0.1, 1.2],
           "link_util_max": 1.2, "loss_max": 0.0,
           "latency_factor_max": 1.5}
    blk = bench_fluid_block(rep)
    assert blk == {"bg_bytes": 900, "bg_dropped": 100,
                   "delivered_share": 0.9, "link_util_max": 1.2}
    s = background_share_sentence(rep, 100)
    assert "90.0%" in s and "900" in s
    assert "no foreground" in background_share_sentence(rep, None)


def test_bench_compare_fluid_findings(tmp_path):
    import subprocess
    import sys

    old = [{"metric": "m", "value": 10.0,
            "fluid": {"bg_bytes": 1000, "bg_dropped": 0}}]
    new_lost = [{"metric": "m", "value": 10.0}]
    new_shrunk = [{"metric": "m", "value": 10.0,
                   "fluid": {"bg_bytes": 100, "bg_dropped": 5}}]
    po, pl, ps = (tmp_path / n for n in ("old.json", "lost.json",
                                         "shrunk.json"))
    po.write_text(json.dumps(old))
    pl.write_text(json.dumps(new_lost))
    ps.write_text(json.dumps(new_shrunk))
    for new_path, needle in ((pl, "coverage lost"),
                             (ps, "coverage shrank")):
        proc = subprocess.run(
            [sys.executable, os.path.join(_REPO, "tools",
                                          "bench_compare.py"),
             str(po), str(new_path), "--json"],
            capture_output=True, text=True, cwd=_REPO,
        )
        assert proc.returncode == 0, proc.stderr  # warnings, not failures
        out = json.loads(proc.stdout)
        assert any(
            f["kind"] == "fluid" and needle in f["detail"]
            for f in out["findings"]
        ), out


def test_heartbeat_bg_regex_and_strict_roundtrip(tmp_path):
    """The bg= field round-trips parse_shadow --strict, alone and with
    the other observatory fields (the R5 runtime half)."""
    import sys
    sys.path.insert(0, _REPO)
    from tools.parse_shadow import parse_heartbeats
    from shadow_tpu.sim import heartbeat_line

    lines = [
        heartbeat_line(2 * 10**9, 3.0, 99, 80, 40, 4096, 7,
                       bg=(123456, 789)),
        heartbeat_line(2 * 10**9, 3.0, 99, 80, 40, 4096, 7,
                       ek=(31, 52), fct=12, bg=(5, 0), iv=(0, 0)),
    ]
    p = tmp_path / "log.txt"
    p.write_text("\n".join(lines) + "\n")
    beats = parse_heartbeats(str(p), strict=True)
    assert len(beats) == 2
    assert beats[0]["bg_bytes"] == 123456
    assert beats[0]["bg_dropped"] == 789
    assert beats[1]["bg_bytes"] == 5 and beats[1]["ek_timer"] == 31


# ---------------------------------------------------------------------------
# compiled-Simulation smoke (subprocess-isolated): zone resolution,
# fluid{} sim-stats block, bg= heartbeat emission
# ---------------------------------------------------------------------------

_SIM_SCRIPT = """
import io, json, sys
from shadow_tpu.config.options import ConfigOptions
from shadow_tpu.sim import Simulation

data_dir = sys.argv[1]
cfg = {
  'general': {'stop_time': '2 s', 'seed': 1, 'data_directory': data_dir,
              'heartbeat_interval': '500 ms'},
  'experimental': {'event_queue_capacity': 32, 'rounds_per_chunk': 16},
  'fluid': {'link_capacity': '5 Mbit', 'latency_factor_max': 1.3,
            'util_threshold': 0.5,
            'classes': [{'src_zone': 0, 'dst_zone': 0,
                         'rate': '10 Mbit', 'start': 0}]},
  'hosts': {'node': {'count': 6, 'network_node_id': 0,
    'processes': [{'model': 'phold',
                   'model_args': {'population': 2, 'mean_delay': '50 ms',
                                  'size_bytes': 64}}]}},
}
log = io.StringIO()
sim = Simulation(ConfigOptions.from_dict(cfg), world=1)
rep = sim.run(log=log)
sim.write_outputs(report=rep)
fl = rep['fluid']
print(json.dumps({
    'bg_bytes': fl['bg_bytes'], 'bg_dropped': fl['bg_dropped'],
    'classes': fl['classes'], 'links': fl['links'],
    'util_max': fl['link_util_max'],
    'heartbeat_bg': sum('bg=' in ln for ln in log.getvalue().splitlines()),
    'digest': rep['determinism_digest'],
}))
"""


def test_simulation_fluid_smoke(tmp_path):
    from tests.subproc import run_isolated_json

    out = run_isolated_json(_SIM_SCRIPT, str(tmp_path / "data"))
    assert out["classes"] == 1 and out["links"] == 1
    assert out["bg_bytes"] > 0
    assert out["bg_dropped"] > 0  # 10 Mbit into a 5 Mbit link clips
    assert out["util_max"] > 0.5
    assert out["heartbeat_bg"] > 0  # bg= rode the heartbeat lines
    stats = json.load(
        open(os.path.join(str(tmp_path / "data"), "sim-stats.json"))
    )
    assert stats["fluid"]["bg_bytes"] == out["bg_bytes"]
    assert stats["fluid"]["delivered_share"] is not None
