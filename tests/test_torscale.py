"""tor-minimal-scale: 104 real processes in one simulation (reference
src/test/tor/minimal/tor-minimal.yaml — many managed processes over a
multi-node graph for tens of simulated seconds). 4 epoll relay servers +
100 udp clients in 4 cross-node groups, parallel host plane
(host_workers: 4), every process self-verifying its traffic. This is the
fd/shmem-pressure proof for the co-optation plane: 100+ concurrent shims,
each with an IPC block, heap window, and captured stdio."""

from __future__ import annotations

import os

import pytest
import yaml

from shadow_tpu.config.options import ConfigOptions
from shadow_tpu.cosim import HybridSimulation

from tests.subproc import native_plane_skip_reason

# toolchain-unavailable OR the shim-cannot-load (exit-97) container
# (tests/subproc.py native_plane_skip_reason classifies the signature)
_skip = native_plane_skip_reason()
pytestmark = pytest.mark.skipif(_skip is not None, reason=str(_skip))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_cfg() -> ConfigOptions:
    with open(os.path.join(REPO, "examples", "tor-minimal.yaml")) as f:
        d = yaml.safe_load(f)
    # absolutize the example's repo-relative paths for any test cwd
    d["network"]["graph"]["path"] = os.path.join(
        REPO, "examples", "graphs", "backbone4.gml"
    )
    for h in d["hosts"].values():
        for p in h.get("processes", []):
            if p["path"].startswith("./"):
                p["path"] = os.path.join(REPO, p["path"][2:])
    return ConfigOptions.from_dict(d)


def _run():
    sim = HybridSimulation(_load_cfg(), world=1)
    r = sim.run(progress=False)
    relay_out = b"".join(
        b"".join(p.stdout)
        for h in sim.hosts
        if h.name.startswith("relay")
        for p in h.processes.values()
    )
    return r, relay_out


def test_104_process_mixed_workload_deterministic():
    r, relay_out = _run()
    assert r["process_failures"] == 0
    assert r["processes_exited"] == 104  # every relay AND client exited 0
    # 100 clients x 60 pings, each echoed: request + reply cross the mesh
    assert r["packets_delivered"] == 12000
    # each relay served exactly its group's 25 x 60 pings
    assert relay_out.count(b"done pings=1500") == 4

    r2, relay_out2 = _run()
    assert r2["determinism_digest"] == r["determinism_digest"]
    assert r2["packets_delivered"] == r["packets_delivered"]
    assert r2["syscalls"] == r["syscalls"]
    assert relay_out2 == relay_out  # byte-identical stdout incl. sim times
