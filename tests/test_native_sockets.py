"""Real binaries communicating over the simulated network (reference: the
socket test family run under Shadow, src/test/socket/ + src/test/tcp/)."""

from __future__ import annotations

import os

import pytest

from shadow_tpu.host import CpuHost, HostConfig
from shadow_tpu.host.network import CpuNetwork

from tests.subproc import native_plane_skip_reason

# toolchain-unavailable OR the shim-cannot-load (exit-97) container
# (tests/subproc.py native_plane_skip_reason classifies the signature)
_skip = native_plane_skip_reason()
pytestmark = pytest.mark.skipif(_skip is not None, reason=str(_skip))

from shadow_tpu.native_plane import spawn_native  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
UDP_ECHO = os.path.join(REPO, "native", "build", "test_udp_echo")
UDP_CLIENT = os.path.join(REPO, "native", "build", "test_udp_client")
TCP_STREAM = os.path.join(REPO, "native", "build", "test_tcp_stream")

MS = 1_000_000
SEC = 1_000_000_000


def two_hosts(lat_ms=25, loss=0.0, seed=7):
    hosts = [
        CpuHost(HostConfig(name=f"h{i}", ip=f"10.0.0.{i + 1}", seed=seed, host_id=i))
        for i in range(2)
    ]
    net = CpuNetwork(
        hosts,
        latency_ns=lambda s, d: lat_ms * MS,
        loss=(lambda s, d: loss) if loss else None,
    )
    return hosts, net


def test_real_udp_binaries_over_simulated_wire():
    hosts, net = two_hosts(lat_ms=25)
    srv = spawn_native(hosts[0], [UDP_ECHO, "9000", "3"])
    cli = spawn_native(
        hosts[1], [UDP_CLIENT, "10.0.0.1", "9000", "3"], start_time=50 * MS
    )
    net.run(5 * SEC)
    assert srv.exit_code == 0 and cli.exit_code == 0
    out = b"".join(cli.stdout).decode()
    # RTT is exactly 2 x 25ms of SIMULATED time for every ping
    assert out.count("rtt_ns=50000000") == 3
    assert "PING 2" in out


def test_real_udp_binaries_deterministic():
    def once():
        hosts, net = two_hosts()
        srv = spawn_native(hosts[0], [UDP_ECHO, "9000", "2"])
        cli = spawn_native(
            hosts[1], [UDP_CLIENT, "10.0.0.1", "9000", "2"], start_time=10 * MS
        )
        net.run(5 * SEC)
        return (
            b"".join(srv.stdout),
            b"".join(cli.stdout),
            [h.counters for h in hosts],
        )

    assert once() == once()


def test_real_tcp_binaries_transfer_with_loss():
    hosts, net = two_hosts(lat_ms=10, loss=0.02)
    srv = spawn_native(hosts[0], [TCP_STREAM, "server", "8080"])
    cli = spawn_native(
        hosts[1], [TCP_STREAM, "10.0.0.1", "8080", "200000"], start_time=100 * MS
    )
    net.run(120 * SEC)
    assert srv.exit_code == 0, b"".join(srv.stderr)
    assert cli.exit_code == 0, b"".join(cli.stderr)
    srv_out = b"".join(srv.stdout).decode()
    cli_out = b"".join(cli.stdout).decode()
    assert "got 200000 bytes" in srv_out
    # data integrity: receiver checksum equals sender checksum
    sum_srv = srv_out.split("sum ")[1].split()[0]
    sum_cli = cli_out.split("sum ")[1].split()[0]
    assert sum_srv == sum_cli
    assert "from 10.0.0.2" in srv_out


def test_real_tcp_connection_refused():
    hosts, net = two_hosts()
    cli = spawn_native(hosts[1], [TCP_STREAM, "10.0.0.1", "81", "100"])
    net.run(10 * SEC)
    assert cli.exit_code == 1  # perror("connect") path
    assert b"connect" in b"".join(cli.stderr)


def test_real_epoll_timerfd_event_loop():
    """A production-shaped epoll event loop (UDP socket + periodic timerfd)
    in a real binary, fully under simulated time (reference epoll/ +
    timerfd/ test families)."""
    EPOLL_SRV = os.path.join(REPO, "native", "build", "test_epoll_server")
    hosts, net = two_hosts(lat_ms=20)
    srv = spawn_native(hosts[0], [EPOLL_SRV, "9000", "2", "3"])
    cli = spawn_native(
        hosts[1], [UDP_CLIENT, "10.0.0.1", "9000", "2"], start_time=50 * MS
    )
    net.run(5 * SEC)
    assert srv.exit_code == 0, b"".join(srv.stderr)
    assert cli.exit_code == 0
    out = b"".join(srv.stdout).decode()
    # timer ticks land exactly on the 200ms grid of SIMULATED time
    assert "tick 1 t=200000000" in out
    assert "tick 3 t=600000000" in out
    # first ping: client start (50ms) + one-way latency (20ms)
    assert "ping 1 t=70000000" in out
    assert "done pings=2 ticks=3" in out


def test_real_binaries_over_device_plane():
    """The full story: real Linux processes exchanging packets through the
    TPU device network plane (cosim bridge)."""
    from shadow_tpu.config.options import ConfigOptions
    from shadow_tpu.cosim import HybridSimulation

    cfg_dict = {
        "general": {"stop_time": "3 s", "seed": 8},
        "network": {"graph": {"type": "1_gbit_switch"}},
        "hosts": {
            "server": {
                "network_node_id": 0,
                "processes": [
                    {
                        "path": UDP_ECHO,
                        "args": ["9000", "2"],
                        "expected_final_state": {"exited": 0},
                    }
                ],
            },
            "client": {"network_node_id": 0, "processes": [{"path": UDP_ECHO}]},
        },
    }
    # first build resolves the server's simulated IP; then point the client
    cfg = ConfigOptions.from_dict(cfg_dict)
    server_ip = next(
        s.ip for s in HybridSimulation(cfg).specs if s.name == "server"
    )
    cfg = ConfigOptions.from_dict(cfg_dict)
    client = next(h for h in cfg.hosts if h.name == "client")
    client.processes[0].path = UDP_CLIENT
    client.processes[0].args = [server_ip, "9000", "2"]
    client.processes[0].expected_final_state = {"exited": 0}
    sim = HybridSimulation(cfg)
    report = sim.run()
    assert report["process_failures"] == 0
    assert report["packets_delivered"] == 4
    outs = [b"".join(p.stdout).decode() for p in sim.procs]
    assert any("client done" in o for o in outs)
    assert any("served 2" in o for o in outs)


def test_sockaddr_len_value_result():
    """getsockname with a short caller buffer must truncate the write and
    store back the TRUE length without clobbering adjacent memory (advisor
    finding: full 16-byte sockaddr written regardless of addrlen)."""
    hosts, net = two_hosts()
    p = spawn_native(
        hosts[0],
        [os.path.join(REPO, "native", "build", "test_sockaddr_len")],
    )
    net.run(1 * SEC)
    out = b"".join(p.stdout).decode()
    assert p.exit_code == 0, out + b"".join(p.stderr).decode()
    assert "guard_ok=1 len=16 port=7777" in out
    assert "full len=16 port=7777" in out


def test_writev_on_socket_single_datagram():
    """writev with multiple iovs on a connected-UDP vfd must emit ONE
    datagram (and not ENOSYS) — review finding on the round-2 writev path."""
    hosts, net = two_hosts(lat_ms=10)
    srv = spawn_native(hosts[0], [UDP_ECHO, "9000", "1"])
    cli = spawn_native(
        hosts[1],
        [os.path.join(REPO, "native", "build", "test_writev_sock"),
         "10.0.0.1", "9000"],
        start_time=50 * MS,
    )
    net.run(5 * SEC)
    assert cli.exit_code == 0, b"".join(cli.stderr)
    # server echoes the datagram uppercased-prefix style ("PING 0"): both
    # iovs arrived in one message
    assert b"echo: PING 0" in b"".join(cli.stdout)
    assert srv.exit_code == 0


def test_parallel_cpu_network_matches_serial_native():
    """CpuNetwork(workers=2): real binaries on a threaded host plane must be
    byte-identical to the serial run (staged cross-host merge in host order)."""

    def once(workers):
        hosts, _ = two_hosts()
        from shadow_tpu.host.network import CpuNetwork

        net = CpuNetwork(
            hosts, latency_ns=lambda s, d: 25 * MS, workers=workers
        )
        srv = spawn_native(hosts[0], [UDP_ECHO, "9000", "2"])
        cli = spawn_native(
            hosts[1], [UDP_CLIENT, "10.0.0.1", "9000", "2"],
            start_time=50 * MS,
        )
        net.run(5 * SEC)
        return (
            srv.exit_code, cli.exit_code,
            b"".join(srv.stdout), b"".join(cli.stdout),
            srv.syscall_count, cli.syscall_count,
        )

    assert once(1) == once(2)
