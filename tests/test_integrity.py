"""Integrity sentinel (`integrity:` config block, core/integrity.py).

Gates, mirroring the ISSUE acceptance:
  - sentinel OFF/ON digest-exactness: digests, per-host event counts,
    and every drop counter bit-identical across echo/phold/tgen x
    flat/bucketed x K{1,4}; world=8 legs run subprocess-isolated
    (tests/subproc.py — this box's documented corruption posture);
  - per-invariant white-box trips: each of the six guards fires on its
    crafted violation (host-side state mutation between chunks) and
    stays quiet on clean runs; the chunk while_loop stops at the
    violating round;
  - deterministic-vs-transient classification: an injected REPRODUCING
    scribble raises IntegrityAbort naming invariant+round+shard with
    last-good artifacts exported; a ONE-SHOT scribble is survived,
    counted in sim-stats integrity{}, and the completed run's digest
    equals an uninjected run's (driver-level, subprocess-isolated);
  - dual digest: a digest-plane flip the primary fold misses is
    classified by core/integrity.classify_digest_pair;
  - heartbeat iv= round-trips through parse_shadow --strict;
  - the corruption-signature taxonomy (tools/corruption.py) classifies
    each documented flavor;
  - examples/integrity.yaml parses; invalid combinations are loud.

Engine-harness legs run in-process (the stable path on this box);
compiled-Simulation legs go through tests/subproc.py. The white-box
trips assert their expected invariant BIT is set rather than the exact
mask — a live corruption wave can legitimately set extra bits, which is
the sentinel doing its job, not a test failure."""

from __future__ import annotations

import json
import os

import jax
import numpy as np
import pytest

from shadow_tpu.core import Engine
from shadow_tpu.core import integrity as ivmod
from shadow_tpu.core.integrity import (
    IV_COUNTER,
    IV_DIGEST,
    IV_EC,
    IV_OUTBOX,
    IV_QFILL,
    IV_TIME,
    classify_digest_pair,
    describe_signature,
    mask_names,
    violation_signature,
    violation_total,
)
from shadow_tpu.config.options import ConfigError, ConfigOptions
from tests.engine_harness import build_sim, mk_hosts

_REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def _run_to_done(model, hosts, stop, *, k=1, qb=0, integrity=False, **kw):
    cfg, m, params, mstate, events = build_sim(
        model, hosts, stop, world=1, queue_block=qb, microstep_events=k,
        integrity=integrity, **kw
    )
    eng = Engine(cfg, m, None)
    state, params = eng.init_state(params, mstate, events, seed=1)
    chunks = 0
    while not bool(state.done):
        state = eng.run_chunk(state, params)
        chunks += 1
        assert chunks < 500
    return state


# short-horizon variants of the established workload trio (the netobs
# matrix shapes): enough rounds to exercise every counter the guards read
_CASES = {
    "phold": ("phold", mk_hosts(8, {"mean_delay": "20 ms", "population": 3}),
              300_000_000, dict(loss=0.1)),
    "echo": ("udp_echo",
             [dict(host_id=0, name="server", start_time=0,
                   model_args={"role": "server"})]
             + [dict(host_id=i, name=f"c{i}", start_time=0,
                     model_args={"role": "client", "peer": "server",
                                 "interval": "4 ms", "size_bytes": 2000})
                for i in range(1, 5)],
             200_000_000, dict(bw_bits=2_000_000, loss=0.05)),
    "tgen": ("tgen_tcp",
             mk_hosts(5, {"flow_segs": 8, "flows": 2, "cwnd_cap": 8,
                          "rto_min": "100 ms"}),
             2_000_000_000,
             dict(loss=0.05, latency=10_000_000, sends_budget=16)),
}


def _matrix_params():
    """World-1 acceptance matrix, tier-1-budgeted like the netobs one:
    the mixed-axis combos add no code path the aligned pairs miss (the
    guards touch layout/K only through values the round already
    computes), so they carry the `slow` mark — the full cross product
    runs under `pytest -m ''`."""
    out = []
    for case in sorted(_CASES):
        for k in (1, 4):
            for qb in (0, 8):
                aligned = (k == 1) == (qb == 0)
                marks = () if aligned else (pytest.mark.slow,)
                out.append(pytest.param(
                    case, k, qb,
                    id=f"{case}-{'flat' if qb == 0 else 'bucketed'}-k{k}",
                    marks=marks,
                ))
    return out


@pytest.mark.parametrize("case,k,qb", _matrix_params())
def test_sentinel_is_bit_identical(case, k, qb):
    """Sentinel ON vs OFF: digests, events, and every drop counter
    bit-identical — the guards only read — and a clean run trips
    nothing (zero violations, virgin signature lanes)."""
    model, hosts, stop, kw = _CASES[case]
    s_off = _run_to_done(model, hosts, stop, k=k, qb=qb, **kw)
    s_on = _run_to_done(model, hosts, stop, k=k, qb=qb, integrity=True, **kw)
    off, on = jax.device_get(s_off.stats), jax.device_get(s_on.stats)

    np.testing.assert_array_equal(np.asarray(off.digest), np.asarray(on.digest))
    np.testing.assert_array_equal(np.asarray(off.events), np.asarray(on.events))
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(s_off.queue.dropped)),
        np.asarray(jax.device_get(s_on.queue.dropped)),
    )
    for field in ("pkts_sent", "pkts_lost", "pkts_codel_dropped",
                  "pkts_budget_dropped", "pkts_delivered", "q_occ_hwm"):
        np.testing.assert_array_equal(
            np.asarray(getattr(off, field)), np.asarray(getattr(on, field)),
            err_msg=field,
        )
    # the ungated program carries NO sentinel lanes; the gated clean run
    # carries virgin ones
    assert off.integrity is None and off.digest2 is None
    assert int(np.asarray(on.integrity).max()) == 0
    assert int(np.asarray(on.iv_mask).max()) == 0
    assert int(np.asarray(on.iv_round).max()) == -1
    # the dual lane is a REAL second fold, not a copy
    assert (np.asarray(on.digest2) != np.asarray(on.digest)).any()


# ---------------------------------------------------------------------------
# world=8 subprocess legs (one layout/K point per axis, netobs posture)
# ---------------------------------------------------------------------------

_W8_SCRIPT = """
import json, sys
import numpy as np
import jax
from shadow_tpu.core import Engine
from tests.engine_harness import build_sim, mk_hosts

qb, k = int(sys.argv[1]), int(sys.argv[2])

def run(integrity):
    cfg, m, params, mstate, events = build_sim(
        "phold", mk_hosts(8, {"mean_delay": "20 ms", "population": 3}),
        300_000_000, world=8, queue_block=qb, microstep_events=k,
        integrity=integrity, loss=0.1)
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:8]), ("hosts",))
    eng = Engine(cfg, m, mesh)
    state, params = eng.init_state(params, mstate, events, seed=1)
    chunks = 0
    while not bool(state.done):
        state = eng.run_chunk(state, params)
        chunks += 1
        assert chunks < 500
    return state

s_off = run(False)
s_on = run(True)
off, on = jax.device_get(s_off.stats), jax.device_get(s_on.stats)
print(json.dumps({
    "digest_equal": bool(
        (np.asarray(off.digest) == np.asarray(on.digest)).all()),
    "events_equal": bool(
        (np.asarray(off.events) == np.asarray(on.events)).all()),
    "violations": int(np.asarray(on.integrity).max()),
    "iv_mask": int(np.asarray(on.iv_mask).max()),
}))
"""


@pytest.mark.parametrize("qb,k", [
    pytest.param(0, 1, id="flat-k1"),
    pytest.param(8, 4, id="bucketed-k4", marks=pytest.mark.slow),
])
def test_sentinel_world8_bit_identical(qb, k):
    from tests.subproc import run_isolated_json

    out = run_isolated_json(_W8_SCRIPT, qb, k, timeout=420)
    assert out["digest_equal"] and out["events_equal"], out
    assert out["violations"] == 0 and out["iv_mask"] == 0, out


# ---------------------------------------------------------------------------
# per-invariant white-box trips + controller classification. BOTH run in
# ONE subprocess each (tests/subproc.py): a scribble-then-redispatch
# sequence under the 8-virtual-device conftest is exactly this box's
# documented corruption magnet — the child prints its verdicts as JSON,
# so a teardown-flavor abort after the result line still yields the
# verdicts, and a mid-run corruption death retries then skips loudly.
# ---------------------------------------------------------------------------

_TRIP_SCRIPT = """
import dataclasses, json
import jax, jax.numpy as jnp
import numpy as np
from shadow_tpu.core import Engine
from shadow_tpu.core import integrity as ivmod
from tests.engine_harness import build_sim, mk_hosts


def phold_engine(qb=0, netobs=False):
    cfg, m, params, mstate, events = build_sim(
        "phold", mk_hosts(8, {"mean_delay": "20 ms", "population": 3}),
        2_000_000_000, loss=0.1, queue_block=qb, netobs=netobs,
        integrity=True,
    )
    cfg = dataclasses.replace(cfg, rounds_per_chunk=8)
    eng = Engine(cfg, m, None)
    state, params = eng.init_state(params, mstate, events, seed=1)
    state = eng.run_chunk(state, params)  # one clean chunk first
    assert not bool(state.done)
    assert int(np.asarray(state.stats.integrity).max()) == 0
    return eng, state, params


def echo_engine_with_idle_host():
    # server + active client + a client that never starts: a host with
    # zero executed events whose digest lanes stay virgin (IV_DIGEST)
    hosts = [
        dict(host_id=0, name="server", start_time=0,
             model_args={"role": "server"}),
        dict(host_id=1, name="c1", start_time=0,
             model_args={"role": "client", "peer": "server",
                         "interval": "4 ms"}),
        dict(host_id=2, name="idle", start_time=99_000_000_000,
             model_args={"role": "client", "peer": "server",
                         "interval": "4 ms"}),
    ]
    cfg, m, params, mstate, events = build_sim(
        "udp_echo", hosts, 4_000_000_000, integrity=True,
    )
    cfg = dataclasses.replace(cfg, rounds_per_chunk=8)
    eng = Engine(cfg, m, None)
    state, params = eng.init_state(params, mstate, events, seed=1)
    state = eng.run_chunk(state, params)
    assert not bool(state.done)
    assert int(np.asarray(jax.device_get(state.stats.events))[2]) == 0
    return eng, state, params


def trip(builder, scribble):
    eng, state, params = builder()
    rounds0 = int(state.stats.rounds)
    state = scribble(state)
    state = eng.run_chunk(state, params)
    return {
        "total": int(np.asarray(state.stats.integrity).max()),
        "mask": int(np.asarray(state.stats.iv_mask).max()),
        "round": int(np.asarray(state.stats.iv_round).max()),
        "rounds0": rounds0,
        "rounds_after": int(state.stats.rounds),
    }


def s_time(st):
    t = np.asarray(jax.device_get(st.queue.t)).copy()
    t[0, 0] = 0  # a past-time event: the window collapses below `now`
    return st._replace(queue=st.queue._replace(t=jnp.asarray(t)))


def s_counter(st):
    ev = np.asarray(jax.device_get(st.stats.events)).copy()
    ev[3] = -7  # negative counter: impossible by construction
    return st._replace(stats=st.stats._replace(events=jnp.asarray(ev)))


def s_outbox(st):
    sr = np.asarray(jax.device_get(st.sent_round)).copy()
    sr[0] = 99  # cursor far past the send budget
    return st._replace(sent_round=jnp.asarray(sr, jnp.int32))


def s_fill(st):
    bf = np.asarray(jax.device_get(st.queue.bfill)).copy()
    bf[0, 0] += 3  # cache no longer matches the slab's occupancy
    return st._replace(
        queue=st.queue._replace(bfill=jnp.asarray(bf, jnp.int32)))


def s_ec(st):
    ec = np.asarray(jax.device_get(st.stats.ec_timer)).copy()
    ec[0] += 5  # class sums no longer reconcile with the event counter
    return st._replace(stats=st.stats._replace(ec_timer=jnp.asarray(ec)))


def s_digest(st):
    dg = np.asarray(jax.device_get(st.stats.digest)).copy()
    dg[2] ^= 1  # the idle host's digest plane scribbled
    return st._replace(stats=st.stats._replace(digest=jnp.asarray(dg)))


def s_digest2(st):
    d2 = np.asarray(jax.device_get(st.stats.digest2)).copy()
    d2[2] ^= 1  # the flip the PRIMARY fold misses: dual lane only
    return st._replace(stats=st.stats._replace(digest2=jnp.asarray(d2)))


CASES = {
    "time_monotonic": (phold_engine, s_time),
    "counter_monotonic": (phold_engine, s_counter),
    "outbox_budget": (phold_engine, s_outbox),
    "queue_fill_cache": (lambda: phold_engine(qb=8), s_fill),
    "event_class_reconcile": (lambda: phold_engine(netobs=True), s_ec),
    "dual_digest_virgin": (echo_engine_with_idle_host, s_digest),
    "dual_digest_flip2": (echo_engine_with_idle_host, s_digest2),
}
import sys
builder, scribbler = CASES[sys.argv[1]]
print(json.dumps(trip(builder, scribbler)))
"""

_TRIP_BITS = {
    "time_monotonic": IV_TIME,
    "counter_monotonic": IV_COUNTER,
    "outbox_budget": IV_OUTBOX,
    "queue_fill_cache": IV_QFILL,
    "event_class_reconcile": IV_EC,
    "dual_digest_virgin": IV_DIGEST,
    "dual_digest_flip2": IV_DIGEST,
}

_trip_results: dict = {}


def _trip_verdict(name, ok_fn):
    """One child per trip (fresh-process exposure — multi-build
    sequences in one process are the documented corruption magnet),
    with the same deviation-classification posture as the drill: a
    deviating verdict retries once in a fresh child; identical
    deviations are a real bug, varying ones are the wave (skip)."""
    from tests.subproc import run_isolated_json

    cached = _trip_results.get(name)
    if cached is not None:
        return cached
    v1 = run_isolated_json(_TRIP_SCRIPT, name, timeout=240)
    if ok_fn(v1):
        _trip_results[name] = v1
        return v1
    v2 = run_isolated_json(_TRIP_SCRIPT, name, timeout=240)
    if ok_fn(v2):
        _trip_results[name] = v2
        return v2
    assert v1 != v2, (
        f"trip '{name}' deviated IDENTICALLY across two fresh child "
        f"processes — a deterministic guard bug, not the documented "
        f"scribble: {v1}"
    )
    pytest.skip(
        f"trip '{name}' children returned varying deviations — the "
        f"documented corruption wave, not a guard verdict: {v1} vs {v2}"
    )


@pytest.mark.parametrize("name", sorted(_TRIP_BITS))
def test_guard_trips_on_crafted_violation(name):
    """Each invariant guard fires on its crafted violation (host-side
    scribble between chunks) and the chunk while_loop stops AT the
    violating round. Asserts the EXPECTED bit is set rather than the
    exact mask: a live corruption wave can legitimately set extra bits,
    which is the sentinel working, not a failure."""
    bit = _TRIP_BITS[name]

    def ok(v):
        return (
            v["total"] > 0
            and bool(v["mask"] & (1 << bit))
            and v["round"] >= v["rounds0"]
            # the violating round completes (and counts), then the loop
            # exits — far short of the 8-round chunk bound
            and v["rounds_after"] == v["round"] + 1
        )

    v = _trip_verdict(name, ok)
    assert v["mask"] & (1 << bit), (
        f"expected bit {bit} ({ivmod.IV_NAMES[bit]}) in mask "
        f"{v['mask']:#x}: {v}"
    )


_CLASSIFY_SCRIPT = """
import dataclasses, json
import jax, jax.numpy as jnp
import numpy as np
from shadow_tpu.core import Engine
from shadow_tpu.core.integrity import IntegrityAbort
from shadow_tpu.core.pressure import ResilienceController
from shadow_tpu.config.options import IntegrityOptions
from tests.engine_harness import build_sim, mk_hosts


def scribble(st):
    t = np.asarray(jax.device_get(st.queue.t)).copy()
    t[0, 0] = 0
    return st._replace(queue=st.queue._replace(t=jnp.asarray(t)))


def run(hook, max_replays=3):
    # ~40 rounds at the harness's 50 ms latency (runahead-bound): the
    # injection lands at rounds >= 16, leaving a couple of chunks to
    # prove survival — kept short, since every extra chunk in one
    # process is corruption exposure on this box (docs/corruption.md)
    cfg, m, params, mstate, events = build_sim(
        "phold", mk_hosts(8, {"mean_delay": "20 ms", "population": 3}),
        2_000_000_000, loss=0.1, integrity=True,
    )
    cfg = dataclasses.replace(cfg, rounds_per_chunk=8)
    eng = Engine(cfg, m, None)
    state, params = eng.init_state(params, mstate, events, seed=1)
    rc = ResilienceController(
        integrity=IntegrityOptions(enabled=True, max_replays=max_replays))
    rc.test_scribble = hook
    err = None
    try:
        chunks = 0
        while not bool(state.done):
            state, _, _ = rc.run_chunk(
                state, lambda s, g, c, b: eng.run_chunk(s, params))
            chunks += 1
            assert chunks < 500
    except IntegrityAbort as e:
        err = str(e)
    d1 = d2 = None
    if err is None:
        d1 = int(np.bitwise_xor.reduce(
            np.asarray(jax.device_get(state.stats.digest))))
        d2 = int(np.bitwise_xor.reduce(
            np.asarray(jax.device_get(state.stats.digest2))))
    return {"transients": rc.iv_transients, "replays": rc.iv_replays,
            "deterministic": rc.iv_deterministic, "error": err,
            "digest": d1, "digest2": d2}


fired = []
def once(st, attempt):
    if attempt == 0 and int(st.stats.rounds) >= 16 and not fired:
        fired.append(1)
        return scribble(st)
    return st


def always(st, attempt):
    if int(st.stats.rounds) >= 16:
        return scribble(st)
    return st


def s_cnt(st):
    ev = np.asarray(jax.device_get(st.stats.events)).copy()
    ev[3] = -7
    return st._replace(stats=st.stats._replace(events=jnp.asarray(ev)))


def s_ob(st):
    sr = np.asarray(jax.device_get(st.sent_round)).copy()
    sr[0] = 99
    return st._replace(sent_round=jnp.asarray(sr, jnp.int32))


count = [0]
def varying(st, attempt):
    # a DIFFERENT invariant each attempt -> a different bitmask in the
    # (shard, round, mask) signature -> never reproduces
    if int(st.stats.rounds) >= 8:
        f = (scribble, s_cnt, s_ob)[count[0] % 3]
        count[0] += 1
        return f(st)
    return st


import sys
mode = sys.argv[1]
if mode == "clean":
    print(json.dumps(run(None)))
elif mode == "once":
    print(json.dumps(run(once)))
elif mode == "repro":
    print(json.dumps(run(always)))
else:
    print(json.dumps(run(varying, max_replays=2)))
"""

_classify_results: dict = {}


def _classify_verdict(mode, ok_fn):
    """One child per mode, with the repo's deviation-classification
    posture (tests/subproc.py, tools/soak.py, docs/corruption.md): the
    injection lands at a KNOWN (round, mask), so any other verdict is
    either this box's documented corruption striking the child (varies
    across fresh processes -> skip) or a real sentinel bug (the SAME
    deviation reproducing across fresh children -> fail)."""
    from tests.subproc import run_isolated_json

    cached = _classify_results.get(mode)
    if cached is not None:
        return cached
    v1 = run_isolated_json(_CLASSIFY_SCRIPT, mode, timeout=300)
    if ok_fn(v1):
        _classify_results[mode] = v1
        return v1
    v2 = run_isolated_json(_CLASSIFY_SCRIPT, mode, timeout=300)
    if ok_fn(v2):
        _classify_results[mode] = v2
        return v2
    assert v1 != v2, (
        f"'{mode}' deviated IDENTICALLY across two fresh child "
        f"processes — a deterministic sentinel bug, not the documented "
        f"scribble: {v1}"
    )
    pytest.skip(
        f"'{mode}' classification children returned varying deviations "
        f"— the documented corruption wave, not a sentinel verdict: "
        f"{v1} vs {v2}"
    )


def _clean_ok(v):
    return (
        not v["error"] and v["transients"] == 0 and v["replays"] == 0
        and v["digest"] is not None
    )


def _clean_verdict():
    return _classify_verdict("clean", _clean_ok)


def test_one_shot_scribble_is_transient_and_survived():
    clean = _clean_verdict()

    def ok(v):
        return (
            v["error"] is None and v["transients"] == 1
            and v["replays"] == 1 and v["deterministic"] is None
            and v["digest"] == clean["digest"]
        )

    once = _classify_verdict("once", ok)
    # the survived run's digests equal the uninjected run's (BOTH lanes)
    assert once["digest"] == clean["digest"]
    assert once["digest2"] == clean["digest2"]


def test_reproducing_scribble_raises_integrity_abort():
    def ok(v):
        msg = v["error"] or ""
        # names the invariant, the INJECTED round, and the shard
        return ("REPRODUCED" in msg and "time_monotonic" in msg
                and "shard 0" in msg and "round 16" in msg
                and v["deterministic"] is not None)

    v = _classify_verdict("repro", ok)
    assert "round 16" in v["error"]


def test_nonreproducing_violations_are_bounded_by_max_replays():
    """A scribble landing at a DIFFERENT invariant every attempt never
    reproduces — the sentinel must still stop after max_replays instead
    of replaying forever."""
    v = _classify_verdict(
        "varying",
        lambda v: bool(v["error"]) and "without reproducing" in v["error"],
    )
    assert "without reproducing" in v["error"]


_CASES_DRILL = (
    "phold", mk_hosts(8, {"mean_delay": "20 ms", "population": 3}),
    2_000_000_000,
)


def _run_to_done_drill():
    return _run_to_done(*_CASES_DRILL, loss=0.1, integrity=True)


# ---------------------------------------------------------------------------
# driver-level classification drill (subprocess-isolated: the sequence
# of compiled Simulations is this box's documented corruption magnet)
# ---------------------------------------------------------------------------

_DRIVER_DRILL = """
import json, sys
import jax.numpy as jnp
import numpy as np
import jax
from shadow_tpu.config.options import ConfigOptions
from shadow_tpu.sim import Simulation

mode, data_dir = sys.argv[1], sys.argv[2]
base = {
  'general': {'stop_time': '2 s', 'seed': 1, 'heartbeat_interval': None,
              'data_directory': data_dir},
  'experimental': {'event_queue_capacity': 32, 'rounds_per_chunk': 8},
  'integrity': {'enabled': True},
  'hosts': {'node': {'count': 8, 'network_node_id': 0,
    'processes': [{'model': 'phold',
                   'model_args': {'population': 2, 'mean_delay': '100 ms',
                                  'size_bytes': 64}}]}},
}

def scribble(st):
    t = np.asarray(jax.device_get(st.queue.t)).copy(); t[0, 0] = 0
    return st._replace(queue=st.queue._replace(t=jnp.asarray(t)))

sim = Simulation(ConfigOptions.from_dict(base), world=1)
fired = []
def hook(st, attempt):
    rounds = int(st.stats.rounds)
    if mode == 'once':
        if attempt == 0 and rounds >= 16 and not fired:
            fired.append(1)
            return scribble(st)
    elif mode == 'repro':
        if rounds >= 16:
            return scribble(st)
    return st
if mode != 'clean':
    sim._integrity_test_scribble = hook
rep = sim.run()
sim.write_outputs(report=rep)
iv = rep.get('integrity') or {}
det = iv.get('deterministic') or {}
print(json.dumps({
    'digest': rep['determinism_digest'],
    'digest2': iv.get('determinism_digest2'),
    'transients': iv.get('transients'),
    'replays': iv.get('replays'),
    'aborted': bool(rep.get('integrity_aborted')),
    'detail': det.get('detail'),
    'rounds': rep['rounds'],
}))
"""


def _drill(mode, tmp_path, tag):
    from tests.subproc import run_isolated_json

    return run_isolated_json(
        _DRIVER_DRILL, mode, str(tmp_path / tag), timeout=300
    )


def test_driver_drill_end_to_end(tmp_path):
    """The acceptance drill: one-shot scribble survived + counted with a
    clean-equal digest; reproducing scribble -> IntegrityAbort naming
    invariant/round/shard with last-good artifacts exported.

    The injection lands at a KNOWN round (16); a violation reported at
    any other round is this box's documented corruption striking the
    worker itself — classified and retried, never judged (the
    classify-then-retry posture, docs/corruption.md). The clean and
    once runs are same-seed deterministic by construction, so their
    digests DISAGREEING is likewise the environment (the wrong-digest
    flavor — observed with VARYING digests on unmodified HEAD during
    PR 12's wave): it routes through tests/subproc.py's deviation
    classification instead of hard-failing tier-1 on the equality
    asserts below."""
    from tests.subproc import classify_deviation, skip_deviation

    attempts = 0
    while True:
        attempts += 1
        clean = _drill("clean", tmp_path, f"clean{attempts}")
        once = _drill("once", tmp_path, f"once{attempts}")
        repro = _drill("repro", tmp_path, f"repro{attempts}")
        # a survived one-shot scribble must land back ON the clean
        # trajectory: digest disagreement between the two runs is the
        # comparison-judged wrong-digest corruption flavor, never a
        # sentinel verdict
        deviated = classify_deviation([
            (clean["digest"], clean["digest2"]),
            (once["digest"], once["digest2"]),
        ])
        env_hit = (
            clean["aborted"] or clean["transients"]
            or once["aborted"]
            or deviated is not None
            or (repro["detail"] or "").find("round 16") < 0
        )
        if not env_hit:
            break
        if attempts >= 3:
            if deviated is not None:
                skip_deviation(
                    "driver drill clean-vs-once digest comparison",
                    attempts,
                    f"clean={clean['digest']}/{clean['digest2']} "
                    f"once={once['digest']}/{once['digest2']}",
                )
            pytest.skip(
                f"driver drill hit the documented corruption wave in "
                f"{attempts}/{attempts} attempts (results: {clean}, "
                f"{once}, {repro}) — environment, not a sentinel verdict"
            )
    # one-shot: survived, counted, digest equal to the clean run's on
    # BOTH digest planes
    assert once["transients"] == 1 and once["replays"] == 1
    assert once["digest"] == clean["digest"]
    assert once["digest2"] == clean["digest2"]
    # reproducing: loud deterministic abort naming invariant+round+shard
    assert repro["aborted"]
    assert "time_monotonic" in repro["detail"]
    assert "shard 0" in repro["detail"] and "round 16" in repro["detail"]
    # last-good artifacts: the export rewound to the pre-chunk snapshot
    # (rounds 16, not the violating attempt), flagged integrity_aborted
    assert repro["rounds"] == 16
    stats = json.load(
        open(os.path.join(str(tmp_path / f"repro{attempts}"),
                          "sim-stats.json"))
    )
    assert stats["integrity_aborted"] and stats["aborted"]
    assert "deterministic" in stats["integrity"]


# ---------------------------------------------------------------------------
# dual-digest pair classification + helpers (pure host side)
# ---------------------------------------------------------------------------


def test_classify_digest_pair():
    assert classify_digest_pair(1, 2, 1, 2) == "clean"
    # primary flipped, dual agrees: the digest plane itself was
    # scribbled — the flavor a single digest cannot see
    assert classify_digest_pair(1 ^ 8, 2, 1, 2) == "digest-plane"
    assert classify_digest_pair(1, 2 ^ 8, 1, 2) == "divergent"
    assert classify_digest_pair(5, 2 ^ 8, 1, 2) == "divergent"
    # without dual folds only clean/divergent are distinguishable
    assert classify_digest_pair(1, None, 1, None) == "clean"
    assert classify_digest_pair(1, None, 2, None) == "divergent"


def test_signature_helpers():
    assert mask_names(1 << IV_TIME) == ["time_monotonic"]
    assert mask_names((1 << IV_EC) | (1 << IV_OUTBOX)) == [
        "event_class_reconcile", "outbox_budget",
    ]
    sig = ((0, 12, 1 << IV_COUNTER),)
    text = describe_signature(sig)
    assert "shard 0" in text and "round 12" in text
    assert "counter_monotonic" in text
    assert describe_signature(()) == "no violating shard recorded"


def test_violation_readers_on_clean_state():
    s = _run_to_done_drill()
    assert violation_total(s) == 0
    assert violation_signature(s) == ()


# ---------------------------------------------------------------------------
# corruption-signature taxonomy (tools/corruption.py — satellite 1)
# ---------------------------------------------------------------------------


def test_corruption_taxonomy_classify():
    from tools import corruption as C

    assert C.classify(134) == C.MALLOC_ABORT
    assert C.classify(-6) == C.MALLOC_ABORT
    assert C.classify(139) == C.SIGSEGV
    assert C.classify(-11) == C.SIGSEGV
    assert C.classify(timed_out=True) == C.TIMEOUT_HANG
    assert C.classify(1) is None and C.classify(0) is None
    # a worker that produced a verdict is never classified away
    assert C.classify(134, output="ok\n") is None
    assert C.classify(134, output=b"result") is None
    assert C.classify(134, output="   \n") == C.MALLOC_ABORT
    assert C.classify(timed_out=True, output="partial") is None
    assert C.is_corruption_rc(134) and C.is_corruption_rc(-11)
    assert not C.is_corruption_rc(0)
    # the flow-counter-scribble bounds gate
    assert C.counters_scribbled([0, 2, 93824992233120], 0, 2)
    assert C.counters_scribbled([-1, 0], 0, 2)
    assert not C.counters_scribbled([0, 1, 2], 0, 2)
    # the canonical rc set is single-sourced: the re-export in
    # tests/subproc.py IS this set
    from tests.subproc import HEAP_CORRUPTION_RCS

    assert HEAP_CORRUPTION_RCS is C.HEAP_CORRUPTION_RCS


# ---------------------------------------------------------------------------
# heartbeat / lanes / config plumbing
# ---------------------------------------------------------------------------


def test_heartbeat_iv_round_trips_strict(tmp_path):
    from shadow_tpu.sim import heartbeat_line
    from tools.parse_shadow import parse_heartbeats

    lines = [
        heartbeat_line(2_000_000_000, 3.0, 99, 198, 40, 4096, 7, iv=(1, 2)),
        heartbeat_line(2_000_000_000, 3.0, 99, 198, 40, 4096, 7,
                       ek=(31, 52), fct=12, iv=(0, 0), rep=(3, 6)),
        # older formats must still parse byte-identically
        heartbeat_line(2_000_000_000, 3.0, 99, 198, 40, 4096, 7),
    ]
    path = tmp_path / "hb.log"
    path.write_text("\n".join(lines) + "\n")
    hbs = parse_heartbeats(str(path), strict=True)
    assert len(hbs) == 3
    assert hbs[0]["iv_transient"] == 1 and hbs[0]["iv_replays"] == 2
    assert hbs[1]["iv_transient"] == 0 and hbs[1]["rep_done"] == 3
    assert "iv_transient" not in hbs[2]


def test_iv_lanes_registered_and_priced():
    """The new lanes are in the single-source registry with shapes the
    HBM model can price: formula bytes == live carry leaf bytes."""
    from shadow_tpu.core import lanes
    from shadow_tpu.obs import memory as M

    for path in ("stats.integrity", "stats.iv_mask", "stats.iv_round",
                 "stats.digest2"):
        assert path in lanes.STATE_LANES
        assert path in lanes.STATE_LANE_SHAPES
    for f in ("integrity", "iv_mask", "iv_round"):
        assert f in lanes.STATS_EXPORT_EXEMPT

    cfg, m, params, mstate, events = build_sim(
        "phold", mk_hosts(4, {"mean_delay": "50 ms", "population": 2}),
        200_000_000, integrity=True,
    )
    eng = Engine(cfg, m, None)
    state, params = eng.init_state(params, mstate, events, seed=1)
    dims = M.dims_of_config(eng.cfg)
    priced = {
        p: b for comp in M.registered_component_bytes(dims).values()
        for p, b in comp.items()
    }
    for path in ("stats.integrity", "stats.iv_mask", "stats.iv_round",
                 "stats.digest2"):
        obj = state
        for part in path.split("."):
            obj = getattr(obj, part)
        assert priced[path] == M.leaf_nbytes(obj), path


def test_example_config_parses_and_validations_are_loud():
    from shadow_tpu.config.options import load_config

    cfg = load_config(os.path.join(_REPO, "examples", "integrity.yaml"))
    assert cfg.integrity.enabled and cfg.integrity.dual_digest
    assert cfg.integrity.max_replays == 3

    with pytest.raises(ConfigError, match="max_replays"):
        ConfigOptions.from_dict({
            "general": {"stop_time": "1 s"},
            "integrity": {"enabled": True, "max_replays": 0},
            "hosts": {"a": {"network_node_id": 0, "processes": [
                {"model": "phold", "model_args": {}}]}},
        })
    with pytest.raises(ConfigError, match="unknown integrity"):
        ConfigOptions.from_dict({
            "general": {"stop_time": "1 s"},
            "integrity": {"enable": True},
            "hosts": {"a": {"network_node_id": 0, "processes": [
                {"model": "phold", "model_args": {}}]}},
        })

    base = {
        "general": {"stop_time": "1 s"},
        "integrity": {"enabled": True},
        "hosts": {"a": {"network_node_id": 0, "processes": [
            {"model": "phold", "model_args": {}}]}},
    }
    from shadow_tpu.sim import Simulation

    bad = json.loads(json.dumps(base))
    bad["experimental"] = {"scheduler": "cpu-reference"}
    with pytest.raises(ConfigError, match="integrity.*cpu-reference"):
        Simulation(ConfigOptions.from_dict(bad), world=1)

    bad = json.loads(json.dumps(base))
    bad["hosts"]["a"]["host_options"] = {"pcap_enabled": True}
    with pytest.raises(ConfigError, match="integrity.*pcap"):
        Simulation(ConfigOptions.from_dict(bad), world=1)

    bad = json.loads(json.dumps(base))
    bad["campaign"] = {"seeds": [1, 2]}
    from tools.campaign import build_campaign

    with pytest.raises(ConfigError, match="integrity"):
        build_campaign(bad)


def test_hybrid_sentinel_rides_the_device_plane():
    """cosim: the device-plane guards trace into the guarded windows
    (integrity_strict_time relaxed), the bridge guards run host-side,
    and a clean hybrid run is digest-identical with the sentinel on,
    zero violations, with the integrity block in its report."""
    from shadow_tpu.cosim import HybridSimulation

    hosts = {
        "server": {
            "network_node_id": 0,
            "processes": [{"path": "udp_echo_server", "args": ["port=9000"]}],
        },
        "client": {
            "network_node_id": 0,
            "processes": [{
                "path": "udp_ping",
                "args": ["server=server", "port=9000", "count=3"],
                "expected_final_state": {"exited": 0},
            }],
        },
    }

    def run(integrity):
        d = {
            "general": {"stop_time": "3 s", "seed": 7},
            "network": {"graph": {"type": "1_gbit_switch"}},
            "hosts": json.loads(json.dumps(hosts)),
        }
        if integrity:
            d["integrity"] = {"enabled": True}
        sim = HybridSimulation(ConfigOptions.from_dict(d))
        rep = sim.run()
        return sim, rep

    sim_off, rep_off = run(False)
    sim_on, rep_on = run(True)
    assert rep_on["determinism_digest"] == rep_off["determinism_digest"]
    assert sim_on.engine_cfg.integrity
    assert not sim_on.engine_cfg.integrity_strict_time
    assert violation_total(sim_on.state) == 0
    assert "integrity" in rep_on and "integrity" not in rep_off
    assert "determinism_digest2" in rep_on["integrity"]
    assert not rep_on.get("integrity_aborted")
    # the bridge guard's committed horizon advanced with the run
    assert sim_on._iv_horizon > 0


def test_engine_config_validation():
    from shadow_tpu.core.engine import EngineConfig

    with pytest.raises(ValueError, match="integrity_dual"):
        EngineConfig(num_hosts=4, stop_time=1, integrity_dual=True)


def test_bench_compare_flags_deterministic_violation(tmp_path):
    """bench_compare: deterministic violation appearing = regression;
    transient growth = warning only (satellite 4)."""
    from tools.bench_compare import compare, _rows

    old = _rows([{
        "metric": "m", "value": 10.0,
        "integrity": {"transients": 0, "replays": 0},
    }])
    new_det = _rows([{
        "metric": "m", "value": 10.0,
        "integrity": {"transients": 0, "replays": 1,
                      "deterministic": {"detail": "shard 0: x at round 3"}},
        "integrity_aborted": True,
    }])
    findings = compare(old, new_det, 0.10, 0.10)
    regs = [f for f in findings if f["severity"] == "regression"]
    assert any(f["kind"] == "integrity" for f in regs), findings

    new_warn = _rows([{
        "metric": "m", "value": 10.0,
        "integrity": {"transients": 4, "replays": 4},
    }])
    findings = compare(old, new_warn, 0.10, 0.10)
    assert not [f for f in findings if f["severity"] == "regression"]
    assert any(
        f["kind"] == "integrity" and f["severity"] == "warning"
        for f in findings
    ), findings
