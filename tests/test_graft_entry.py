"""Guard the driver integration hooks in __graft_entry__.py.

The driver's only multi-chip evidence is `dryrun_multichip`; round 1 shipped a
version that asserted on real device count and went red on the driver's box
(MULTICHIP_r01.json ok=false). This test imports the actual module the driver
runs so the hooks can never rot silently again.
"""

import pathlib
import sys

import jax
import pytest

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import __graft_entry__ as graft  # noqa: E402


def test_entry_compiles_and_runs():
    fn, (state, params) = graft.entry()
    out = jax.jit(fn)(state, params)
    jax.block_until_ready(out)
    assert int(out.stats.rounds) > 0
    assert int(out.now) > 0


def test_dryrun_multichip_8():
    # conftest already forces the 8-device virtual CPU mesh; dryrun must also
    # work when invoked cold by the driver, but here we at least prove the
    # sharded chunk compiles + executes and reports progress.
    graft.dryrun_multichip(8)


def test_dryrun_multichip_forces_mesh_in_fresh_process():
    """Run dryrun the way the driver does: a bare `python -c` with no help."""
    import subprocess

    repo = pathlib.Path(__file__).resolve().parent.parent
    proc = subprocess.run(
        [
            sys.executable,
            "-c",
            "import __graft_entry__; __graft_entry__.dryrun_multichip(8)",
        ],
        cwd=repo,
        env={
            k: v
            for k, v in __import__("os").environ.items()
            if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
        },
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
