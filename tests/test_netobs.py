"""Network observatory (`observability.network`, shadow_tpu/obs/netobs.py).

Gates, mirroring the ISSUE acceptance:
  - observer exactness: digests, per-host event counts, and every drop
    counter are bit-identical with the observatory (and flow ledger) on
    vs off, across echo/phold/tgen x flat/bucketed x K{1,4}; the
    world=8 legs run subprocess-isolated (tests/subproc.py, this box's
    documented jaxlib-0.4.37 corruption posture) with one layout/K
    point per model covering both axes;
  - event-class totals reconcile exactly: ec_timer + ec_pkt + ec_app ==
    stats.events, and the per-round trace columns sum to the same;
  - the flow ledger reconciles exactly: drained record totals ==
    fl_done/fl_bytes/fl_rtx stats lanes == the model's own flows_done,
    wrap losses are counted (never silent), and a collector synced to a
    mid-run cursor never replays pre-sync records (the checkpoint-resume
    contract);
  - safe-window telemetry: win_bound counts cover every round;
  - heartbeat ek=/fct= round-trip through parse_shadow --strict;
  - a compiled-Simulation smoke (subprocess-isolated) exports the
    network{} block, the flow track, and artifacts tools/net_report.py
    and tools/trace_summary.py consume.

Engine-harness legs run in-process (the stable path on this box);
compiled-Simulation legs go through tests/subproc.py."""

from __future__ import annotations

import json
import os
import subprocess
import sys

import jax
import numpy as np
import pytest

from shadow_tpu.core import Engine
from shadow_tpu.obs.netobs import (
    FCOL_BYTES,
    FCOL_DST,
    FCOL_RETRANSMITS,
    FCOL_SRC,
    FCOL_T_END,
    FCOL_T_START,
    FLOW_COLS,
    FlowCollector,
    bench_network_block,
    event_class_report,
    fct_stats,
    link_hwm,
    network_report,
)
from shadow_tpu.obs.tracer import (
    COL_BIND_SHARD,
    COL_EC_APP,
    COL_EC_PKT,
    COL_EC_TIMER,
    COL_FLOWS,
    RoundTracer,
)
from tests.engine_harness import build_sim, mk_hosts

_REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
RING = 64


def _run(model, hosts, stop, *, k=1, qb=0, netobs=False, flow_records=0,
         trace=False, **kw):
    cfg, m, params, mstate, events = build_sim(
        model, hosts, stop, world=1, queue_block=qb, microstep_events=k,
        netobs=netobs, flow_records=flow_records,
        trace_rounds=(RING if trace else 0), **kw
    )
    eng = Engine(cfg, m, None)
    state, params = eng.init_state(params, mstate, events, seed=1)
    chunks = 0
    while not bool(state.done):
        state = eng.run_chunk(state, params)
        chunks += 1
        assert chunks < 500
    return state


# short-horizon variants of the tracer's workload trio: enough rounds to
# exercise timers, retransmits, and flow completions, small enough for
# the 24-build matrix
_CASES = {
    "phold": ("phold", mk_hosts(8, {"mean_delay": "20 ms", "population": 3}),
              300_000_000, dict(loss=0.1)),
    "echo": ("udp_echo",
             [dict(host_id=0, name="server", start_time=0,
                   model_args={"role": "server"})]
             + [dict(host_id=i, name=f"c{i}", start_time=0,
                     model_args={"role": "client", "peer": "server",
                                 "interval": "4 ms", "size_bytes": 2000})
                for i in range(1, 5)],
             200_000_000, dict(bw_bits=2_000_000, loss=0.05)),
    "tgen": ("tgen_tcp",
             mk_hosts(5, {"flow_segs": 8, "flows": 2, "cwnd_cap": 8,
                          "rto_min": "100 ms"}),
             2_000_000_000,
             dict(loss=0.05, latency=10_000_000, sends_budget=16)),
}


def _flow_records_for(model):
    return 64 if model == "tgen_tcp" else 0


def _matrix_params():
    """The world-1 acceptance matrix. Tier-1 wall budget on this box is
    the binding constraint (the 870 s gate already cuts the suite), so
    the mixed-axis combos — (flat, k4) and (bucketed, k1), which add no
    code path the aligned pairs miss (netobs touches layout/K only
    through the shared microstep body) — carry the `slow` mark: the
    FULL cross product runs under `pytest -m ''`, tier-1 runs the
    aligned half plus the world-8 legs."""
    out = []
    for case in sorted(_CASES):
        for k in (1, 4):
            for qb in (0, 8):
                aligned = (k == 1) == (qb == 0)
                marks = () if aligned else (pytest.mark.slow,)
                out.append(pytest.param(
                    case, k, qb,
                    id=f"{case}-{'flat' if qb == 0 else 'bucketed'}-k{k}",
                    marks=marks,
                ))
    return out


@pytest.mark.parametrize("case,k,qb", _matrix_params())
def test_netobs_is_bit_identical_and_reconciles(case, k, qb):
    """The ISSUE acceptance gate, world=1: observatory on vs off across
    the model x layout x K matrix, plus class/flow/safe-window
    reconciliation on the gated run."""
    model, hosts, stop, kw = _CASES[case]
    fr = _flow_records_for(model)
    s_off = _run(model, hosts, stop, k=k, qb=qb, **kw)
    s_on = _run(model, hosts, stop, k=k, qb=qb, netobs=True,
                flow_records=fr, **kw)
    off, on = jax.device_get(s_off.stats), jax.device_get(s_on.stats)

    np.testing.assert_array_equal(np.asarray(off.digest), np.asarray(on.digest))
    np.testing.assert_array_equal(np.asarray(off.events), np.asarray(on.events))
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(s_off.queue.dropped)),
        np.asarray(jax.device_get(s_on.queue.dropped)),
    )
    for field in ("pkts_sent", "pkts_lost", "pkts_codel_dropped",
                  "pkts_budget_dropped", "pkts_delivered", "q_occ_hwm"):
        np.testing.assert_array_equal(
            np.asarray(getattr(off, field)), np.asarray(getattr(on, field)),
            err_msg=field,
        )

    # the ungated program carries NO observatory lanes; the gated one
    # reconciles class totals with the event counter exactly
    assert off.ec_timer is None and off.win_bound is None
    ec = (int(np.asarray(on.ec_timer).sum())
          + int(np.asarray(on.ec_pkt).sum())
          + int(np.asarray(on.ec_app).sum()))
    assert ec == int(np.asarray(on.events).sum())
    assert int(np.asarray(on.ec_pkt).sum()) > 0  # every case sends packets

    # safe window: the single shard binds every scheduling round
    assert int(np.asarray(on.win_bound).sum()) == int(on.rounds)

    if fr:
        col = FlowCollector(fr)
        col.drain(s_on.flows)
        r = col.records()
        assert r.shape == (int(np.asarray(on.fl_done).sum()), FLOW_COLS)
        assert int(r[:, FCOL_BYTES].sum()) == int(np.asarray(on.fl_bytes).sum())
        assert int(r[:, FCOL_RETRANSMITS].sum()) == int(
            np.asarray(on.fl_rtx).sum()
        )
        assert (r[:, FCOL_T_END] > r[:, FCOL_T_START]).all()
        assert (r[:, FCOL_SRC] != r[:, FCOL_DST]).all()
        # ledger completions == the model's own flow counter (an
        # independent path: model state vs engine stats vs ring)
        mdl = jax.device_get(s_on.model)
        assert int(np.asarray(mdl["flows_done"]).sum()) == int(
            np.asarray(on.fl_done).sum()
        )
    else:
        assert on.fl_done is None and s_on.flows is None


# world=8 legs: one (layout, K) point per model — between the three legs
# both queue layouts and both K values are covered at world 8; the full
# cross product stays at world 1 above (each 8-device leg costs a heavy
# shard_map compile, and compiled multi-device runs are exactly where
# this box's documented corruption bites, hence tests/subproc.py).
_W8_SCRIPT = """
import json, sys
import numpy as np
import jax
from shadow_tpu.core import Engine
from tests.engine_harness import build_sim, mk_hosts

model, qb, k, fr = sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), int(sys.argv[4])
cases = {
    "phold": ("phold", mk_hosts(8, {"mean_delay": "20 ms", "population": 3}),
              300_000_000, dict(loss=0.1)),
    "udp_echo": ("udp_echo",
        [dict(host_id=0, name="server", start_time=0,
              model_args={"role": "server"})]
        + [dict(host_id=i, name=f"c{i}", start_time=0,
                model_args={"role": "client", "peer": "server",
                            "interval": "4 ms", "size_bytes": 2000})
           for i in range(1, 8)],
        200_000_000, dict(bw_bits=2_000_000, loss=0.05)),
    "tgen_tcp": ("tgen_tcp",
        mk_hosts(8, {"flow_segs": 8, "flows": 1, "cwnd_cap": 8,
                     "rto_min": "100 ms"}),
        1_500_000_000, dict(loss=0.05, latency=10_000_000, sends_budget=16)),
}
name, hosts, stop, kw = cases[model]

def run(netobs):
    cfg, m, params, mstate, events = build_sim(
        name, hosts, stop, world=8, queue_block=qb, microstep_events=k,
        netobs=netobs, flow_records=(fr if netobs else 0), **kw)
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:8]), ("hosts",))
    eng = Engine(cfg, m, mesh)
    state, params = eng.init_state(params, mstate, events, seed=1)
    chunks = 0
    while not bool(state.done):
        state = eng.run_chunk(state, params)
        chunks += 1
        assert chunks < 500
    return state

s_off = run(False)
s_on = run(True)
off, on = jax.device_get(s_off.stats), jax.device_get(s_on.stats)
out = {
    "digest_equal": bool(
        (np.asarray(off.digest) == np.asarray(on.digest)).all()),
    "events_equal": bool(
        (np.asarray(off.events) == np.asarray(on.events)).all()),
    "dropped_equal": bool((
        np.asarray(jax.device_get(s_off.queue.dropped))
        == np.asarray(jax.device_get(s_on.queue.dropped))).all()),
    "events": int(np.asarray(on.events).sum()),
    "ec_total": int(np.asarray(on.ec_timer).sum())
    + int(np.asarray(on.ec_pkt).sum()) + int(np.asarray(on.ec_app).sum()),
    "rounds": int(on.rounds),
    "win_bound": [int(x) for x in np.asarray(on.win_bound)],
    "fl_done": (int(np.asarray(on.fl_done).sum())
                if on.fl_done is not None else None),
}
print(json.dumps(out))
"""


@pytest.mark.parametrize(
    "model,qb,k,fr",
    [("udp_echo", 0, 1, 0), ("phold", 8, 1, 0), ("tgen_tcp", 0, 4, 64)],
    ids=["echo-flat-k1", "phold-bucketed-k1", "tgen-flat-k4"],
)
def test_netobs_world8_bit_identical(model, qb, k, fr):
    """World-8 observer exactness + reconciliation: the per-shard
    win_bound counts must cover every round exactly once (the binder is
    mesh-uniform with deterministic ties)."""
    from tests.subproc import run_isolated_json

    out = run_isolated_json(_W8_SCRIPT, model, qb, k, fr)
    assert out["digest_equal"], "digests changed with the observatory on"
    assert out["events_equal"] and out["dropped_equal"]
    assert out["ec_total"] == out["events"]
    assert sum(out["win_bound"]) == out["rounds"]
    if fr:
        assert out["fl_done"] is not None and out["fl_done"] >= 0


def test_flow_ledger_wrap_counts_lost_records():
    """A ring smaller than the completions between drains loses the
    OLDEST records and counts them — and the fl_* stats lanes keep the
    exact totals regardless (the independent-path design)."""
    model, hosts, stop, kw = _CASES["tgen"]
    state = _run(model, hosts, stop, netobs=True, flow_records=4, **kw)
    s = jax.device_get(state.stats)
    done = int(np.asarray(s.fl_done).sum())
    assert done > 4  # 5 hosts x 2 flows: the 4-slot ring must wrap
    col = FlowCollector(4)
    n = col.drain(state.flows)
    assert n == 4
    assert col.lost == done - 4
    assert col.count == 4
    r = col.records()
    assert r.shape[0] == 4
    # the survivors are the NEWEST records: completion times beyond the
    # drained set never exceed theirs... (monotone cursor: rows at
    # cursor-4..cursor-1 are the last four appended on this shard)
    assert (r[:, FCOL_T_END] > 0).all()


def test_flow_collector_sync_cursor_never_replays():
    """The checkpoint-resume shape: a FRESH collector handed a ledger
    whose cursor is already advanced must adopt it — not replay
    pre-existing records as new completions or count them as losses."""
    model, hosts, stop, kw = _CASES["tgen"]
    state = _run(model, hosts, stop, netobs=True, flow_records=64, **kw)
    assert int(jax.device_get(state.flows.cursor).max()) > 0
    b = FlowCollector(64)
    b.sync_cursor(state.flows)
    assert b.drain(state.flows) == 0
    assert b.count == 0 and b.lost == 0
    assert b.records().shape[0] == 0


def test_flow_collector_truncate_to_cursor():
    """The graceful-abort shape: drained records beyond an exported
    state's own ledger cursor are dropped, newest first."""
    model, hosts, stop, kw = _CASES["tgen"]
    state = _run(model, hosts, stop, netobs=True, flow_records=64, **kw)
    col = FlowCollector(64)
    n = col.drain(state.flows)
    assert n >= 4
    keep = n - 3
    dropped = col.truncate_to_cursor(np.asarray([keep], np.int64))
    assert dropped == 3
    assert col.records().shape[0] == keep
    # idempotent at the same cursor
    assert col.truncate_to_cursor(np.asarray([keep], np.int64)) == 0


def test_flow_collector_truncate_across_wrap_losses():
    """Truncation must account wrap-lost records by their GLOBAL index —
    a rewind to cursor 0 cannot leave phantom losses or a negative
    count (the review-found over-drop)."""
    model, hosts, stop, kw = _CASES["tgen"]
    state = _run(model, hosts, stop, netobs=True, flow_records=4, **kw)
    done = int(np.asarray(jax.device_get(state.stats.fl_done)).sum())
    assert done > 4
    col = FlowCollector(4)
    col.drain(state.flows)
    assert col.lost == done - 4 and col.count == 4
    # full rewind: every record AND every loss is un-seen
    assert col.truncate_to_cursor(np.asarray([0], np.int64)) == done
    assert col.count == 0 and col.lost == 0
    assert col.records().shape[0] == 0
    # partial rewind INTO the lost range: losses recount to the prefix
    col2 = FlowCollector(4)
    col2.drain(state.flows)
    keep = done - 2  # drops 2 held records, keeps 2 held + all losses
    col2.truncate_to_cursor(np.asarray([keep], np.int64))
    assert col2.lost == done - 4
    assert col2.count == 2
    assert col2.records().shape[0] == 2


def test_trace_ring_carries_event_class_columns():
    """The per-round class/flow columns reconcile with the cumulative
    stats lanes, and bind_shard is 0 on a single shard."""
    model, hosts, stop, kw = _CASES["tgen"]
    cfg, m, params, mstate, events = build_sim(
        model, hosts, stop, world=1, netobs=True, flow_records=64,
        trace_rounds=RING, **kw
    )
    eng = Engine(cfg, m, None)
    state, params = eng.init_state(params, mstate, events, seed=1)
    tracer = RoundTracer(RING)
    chunks = 0
    while not bool(state.done):
        state = eng.run_chunk(state, params)
        jax.block_until_ready(state)
        tracer.drain(state.trace)
        chunks += 1
        assert chunks < 500
    s = jax.device_get(state.stats)
    rows = tracer.rows()[0]
    assert rows[:, COL_EC_TIMER].sum() == int(np.asarray(s.ec_timer).sum())
    assert rows[:, COL_EC_PKT].sum() == int(np.asarray(s.ec_pkt).sum())
    assert rows[:, COL_EC_APP].sum() == int(np.asarray(s.ec_app).sum())
    assert rows[:, COL_FLOWS].sum() == int(np.asarray(s.fl_done).sum())
    assert (rows[:, COL_BIND_SHARD] == 0).all()
    t = tracer.totals()
    assert t["ec_timer"] + t["ec_pkt"] + t["ec_app"] == t["events"]
    assert t["flows"] == int(np.asarray(s.fl_done).sum())


def test_flow_collector_validation():
    with pytest.raises(ValueError, match="ring_records"):
        FlowCollector(0)
    col = FlowCollector(8)
    assert col.count == 0 and col.lost == 0
    assert col.records().shape == (0, FLOW_COLS)
    assert col.summary()["records_drained"] == 0
    assert col.summary()["fct"]["p50_ms"] is None


def test_netobs_report_helpers():
    ec = event_class_report(30, 60, 10)
    assert ec["total"] == 100 and ec["timer_share"] == 0.3
    assert event_class_report(0, 0, 0)["timer_share"] is None
    f = fct_stats(np.asarray([10_000_000, 20_000_000, 30_000_000]))
    assert f["n"] == 3 and f["p50_ms"] == 20.0 and f["max_ms"] == 30.0
    assert link_hwm({}) == {"packets_sent": 0, "bytes": 0}
    assert link_hwm(
        {"0": {"packets_sent": 5, "bytes": 100},
         "1": {"packets_sent": 9, "bytes": 50}}
    ) == {"packets_sent": 9, "bytes": 100}
    net = network_report(
        ec_timer=1, ec_pkt=2, ec_app=3, win_bound=np.asarray([4]),
        rounds=4, fl=(2, 200, 1),
        links={"0": {"hosts": 2, "packets_sent": 7, "bytes": 9}},
    )
    assert net["event_classes"]["total"] == 6
    assert net["safe_window"]["critical_shard"] == 0
    assert net["flows"]["completed"] == 2
    assert net["link_hwm"]["packets_sent"] == 7
    b = bench_network_block(net)
    assert b["flows_completed"] == 2 and "event_classes" in b


def test_engine_config_validates_flow_records():
    from shadow_tpu.core.engine import EngineConfig

    with pytest.raises(ValueError, match="netobs"):
        EngineConfig(num_hosts=4, stop_time=1, flow_records=8)
    with pytest.raises(ValueError, match="flow_records"):
        EngineConfig(num_hosts=4, stop_time=1, netobs=True, flow_records=-1)
    cfg = EngineConfig(num_hosts=4, stop_time=1, netobs=True, flow_records=8)
    assert cfg.flow_ledger_active
    assert not EngineConfig(
        num_hosts=4, stop_time=1, netobs=True
    ).flow_ledger_active


def test_observability_network_options_parse():
    from shadow_tpu.config.options import ConfigError, ObservabilityOptions

    o = ObservabilityOptions.from_dict(None)
    assert not o.network and o.network_flows == 4096
    o = ObservabilityOptions.from_dict(
        {"network": True, "network_flows": 128}
    )
    assert o.network and o.network_flows == 128
    # 0 = ledger off, observatory still on (the engine's documented
    # flow_records=0 contract reaches the config surface)
    o = ObservabilityOptions.from_dict(
        {"network": True, "network_flows": 0}
    )
    assert o.network and o.network_flows == 0
    with pytest.raises(ConfigError, match="network_flows"):
        ObservabilityOptions.from_dict({"network_flows": -1})


def test_example_netobs_yaml_parses():
    from shadow_tpu.config.options import load_config

    cfg = load_config(os.path.join(_REPO, "examples", "netobs.yaml"))
    assert cfg.observability.network
    assert cfg.observability.network_flows == 1024
    assert cfg.observability.trace


def test_heartbeat_ek_fct_regex_and_strict_roundtrip(tmp_path):
    """The ek=/fct= fields parse, older generations keep parsing, and a
    line emitted by heartbeat_line round-trips through --strict."""
    sys.path.insert(0, _REPO)
    from tools.parse_shadow import HEARTBEAT_RE, parse_heartbeats
    from shadow_tpu.sim import heartbeat_line

    line = heartbeat_line(
        2_000_000_000, 3.0, 99, 80, 40, 4096, 7,
        ek=(31, 52), fct=12,
    )
    m = HEARTBEAT_RE.search(line)
    assert m and m.group("ek_timer") == "31" and m.group("ek_pkt") == "52"
    assert m.group("fct_done") == "12"
    # older generation without the fields still parses
    old = ("[heartbeat] sim_time=1.000s wall=2.50s events=100 rounds=10 "
           "msteps/round=3.0 ev/mstep=3.33 ici_bytes=4096 q_hwm=7 "
           "ratio=0.40x")
    m = HEARTBEAT_RE.search(old)
    assert m and m.group("ek_timer") is None and m.group("fct_done") is None
    # hybrid windows form with ek
    hyb = ("[heartbeat] sim_time=2.000s wall=3.00s windows=12 gear=2 "
           "ek=31/52 ratio=0.67x")
    m = HEARTBEAT_RE.search(hyb)
    assert m and m.group("ek_timer") == "31" and m.group("windows") == "12"
    # strict round-trip (the R5 runtime half)
    log = tmp_path / "run.log"
    log.write_text(line + "\n" + old + "\n" + hyb + "\n")
    hbs = parse_heartbeats(str(log), strict=True)
    assert len(hbs) == 3
    assert hbs[0]["ek_timer"] == 31 and hbs[0]["fct_done"] == 12


def test_bench_compare_network_block(tmp_path):
    """FCT/retransmit/link-hwm growth fail the diff; share drift warns."""
    sys.path.insert(0, _REPO)
    from tools.bench_compare import compare, _rows

    def row(p50, p99, rtx, hwm, share):
        return {"metric": "m", "value": 10.0, "network": {
            "event_classes": {"timer": 10, "packet": 80, "app": 10,
                              "timer_share": share},
            "fct": {"p50_ms": p50, "p99_ms": p99},
            "retransmits": rtx,
            "link_hwm": {"packets_sent": hwm, "bytes": hwm * 100},
        }}

    old = _rows([row(10.0, 40.0, 5, 1000, 0.10)])
    # regression: p99 +50%, retransmits x3, link hwm +50%
    new = _rows([row(10.0, 60.0, 15, 1500, 0.30)])
    findings = compare(old, new, 0.10, 0.10)
    kinds = {(f["kind"], f["severity"]) for f in findings}
    assert ("network", "regression") in kinds
    details = " | ".join(f["detail"] for f in findings)
    assert "fct p99" in details and "retransmits" in details
    assert "link hot-spot" in details
    assert any(f["severity"] == "warning" and "share" in f["detail"]
               for f in findings)
    # identical blocks: no network findings at all
    same = compare(old, _rows([row(10.0, 40.0, 5, 1000, 0.10)]), 0.1, 0.1)
    assert not [f for f in same if f["kind"] == "network"]
    # losing the block entirely is a coverage warning
    lost = _rows([{"metric": "m", "value": 10.0}])
    findings = compare(old, lost, 0.1, 0.1)
    assert any(f["kind"] == "network" and f["severity"] == "warning"
               for f in findings)


# the compiled-Simulation smoke runs in a SUBPROCESS via tests/subproc.py
# (the shared isolation for this box's documented jaxlib-0.4.37 heap
# corruption in compiled Simulation runs). The engine-harness matrix
# above is the primary gate; this leg gates the DRIVER wiring: config ->
# engine statics, chunk-boundary drains, sim-stats network{} block,
# host-stats extras, and the exported artifacts.
_SMOKE_SCRIPT = """
import json, sys
from shadow_tpu.config.options import ConfigOptions
from shadow_tpu.sim import Simulation

def cfg(tmp, network):
    return ConfigOptions.from_dict({
        "general": {"stop_time": "3 s", "seed": 7, "data_directory": tmp,
                    "heartbeat_interval": None},
        "network": {"graph": {"type": "1_gbit_switch"}},
        "experimental": {"event_queue_capacity": 32,
                         "sends_per_host_round": 16,
                         "rounds_per_chunk": 16},
        "observability": {"trace": network, "network": network,
                          "network_flows": 64},
        "hosts": {
            "node": {"count": 5, "network_node_id": 0,
                     "processes": [{
                         "model": "tgen_tcp",
                         "model_args": {"flows": 2, "flow_segs": 8,
                                        "cwnd_cap": 8,
                                        "rto_min": "100 ms"}}]},
        },
    })

off_dir, on_dir = sys.argv[1], sys.argv[2]
sim_off = Simulation(cfg(off_dir, False), world=1)
rep_off = sim_off.run()
sim_on = Simulation(cfg(on_dir, True), world=1)
rep_on = sim_on.run()
# scribble gate (tools/net_report.py run_check documents it): this box's
# silent-corruption flavor scrawls pointer garbage over small model
# lanes in in-process compiled-Simulation sequences (reproduced on
# unmodified HEAD). A per-host flow counter outside [0, flows=2] is
# physically impossible — classify instead of false-failing the
# reconciliation asserts.
import jax, numpy as np
for sim in (sim_off, sim_on):
    fd = np.asarray(jax.device_get(sim.state.model["flows_done"]))
    if (fd < 0).any() or (fd > 2).any():
        print(json.dumps({"poisoned": fd.tolist()}))
        raise SystemExit(0)
sim_on.write_outputs(report=rep_on)
print(json.dumps({"off": rep_off, "on": rep_on}))
"""


def test_simulation_netobs_smoke(tmp_path):
    """Tier-1 driver smoke (the ISSUE's CI satellite): a tiny tgen sim
    with the observatory on matches the off-run's digests, exports a
    reconciling network{} block, and produces artifacts net_report.py
    and trace_summary.py consume."""
    from tests.subproc import run_isolated_json

    for attempt in range(3):
        reps = run_isolated_json(
            _SMOKE_SCRIPT, str(tmp_path / "off"), str(tmp_path / "on")
        )
        if "poisoned" not in reps:
            break
    else:
        pytest.skip(
            "known jaxlib-0.4.37 silent-scribble corruption poisoned the "
            f"model lanes in 3/3 attempts (reproduced on unmodified HEAD; "
            f"CHANGES.md env notes): {reps['poisoned']}"
        )
    rep_off, rep_on = reps["off"], reps["on"]

    assert rep_on["determinism_digest"] == rep_off["determinism_digest"]
    assert rep_on["events_processed"] == rep_off["events_processed"]
    assert "network" not in rep_off
    net = rep_on["network"]
    assert net["event_classes"]["total"] == rep_on["events_processed"]
    assert net["event_classes"]["timer"] >= 0
    assert net["event_classes"]["packet"] > 0
    flows = net["flows"]
    assert flows["completed"] == rep_on["model_report"]["flows_completed"]
    assert flows["records_drained"] + flows["records_lost"] \
        == flows["completed"]
    assert flows["fct"]["p50_ms"] is not None
    assert sum(net["safe_window"]["bound_rounds_per_shard"]) \
        == rep_on["rounds"]
    assert "0" in net["links"]
    assert net["links"]["0"]["hosts"] == 5
    assert net["links"]["0"]["packets_sent"] == rep_on["packets_sent"]
    assert net["link_hwm"]["packets_sent"] > 0

    # host-stats carries the per-host network extras on gated runs
    hs = json.load(open(tmp_path / "on" / "hosts" / "node1" /
                        "host-stats.json"))
    assert "retransmits" in hs and "bytes" in hs
    assert "packets_codel_dropped" in hs

    # the trace carries the flow track and the class columns
    trace = json.load(open(tmp_path / "on" / "trace.json"))
    flow_ev = [e for e in trace["traceEvents"] if e.get("cat") == "flow"]
    assert len(flow_ev) == flows["records_drained"]
    assert all(e["dur"] > 0 for e in flow_ev)

    # tools consume the artifacts
    out = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "trace_summary.py"),
         str(tmp_path / "on" / "trace.json"), "--json"],
        capture_output=True, text=True, timeout=60,
    )
    assert out.returncode == 0, out.stderr
    summary = json.loads(out.stdout)
    assert summary["event_classes"]["total"] == rep_on["events_processed"]
    assert summary["event_classes"]["flows_completed"] == flows["completed"]

    out = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "net_report.py"),
         str(tmp_path / "on")],
        capture_output=True, text=True, timeout=60,
    )
    assert out.returncode == 0, out.stderr
    assert "timer-vs-packet share" in out.stdout
    assert "## flows" in out.stdout and "## links" in out.stdout
