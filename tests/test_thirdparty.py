"""Unmodified third-party binaries under the shim (the reference proves
itself on stock applications: examples/apps curl/wget/nginx/... — here the
distro's /usr/bin/curl and /usr/bin/wget complete byte-verified HTTP
transfers over the simulated network against a purpose-written server)."""

from __future__ import annotations

import os

import pytest

from shadow_tpu.host import CpuHost, HostConfig
from shadow_tpu.host.network import CpuNetwork

from tests.subproc import native_plane_skip_reason

# toolchain-unavailable OR the shim-cannot-load (exit-97) container
# (tests/subproc.py native_plane_skip_reason classifies the signature)
_skip = native_plane_skip_reason()
pytestmark = pytest.mark.skipif(_skip is not None, reason=str(_skip))

from shadow_tpu.native_plane import spawn_native  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
HTTPD = os.path.join(REPO, "native", "build", "test_httpd")
CURL = "/usr/bin/curl"
WGET = "/usr/bin/wget"

MS = 1_000_000
SEC = 1_000_000_000


def _expected(n: int) -> bytes:
    block = bytes(ord("A") + (i % 26) for i in range(4096))
    return (block * (n // 4096 + 1))[:n]


def two_hosts(lat_ms=10, seed=7):
    hosts = [
        CpuHost(HostConfig(name=f"h{i}", ip=f"10.0.0.{i + 1}", seed=seed, host_id=i))
        for i in range(2)
    ]
    net = CpuNetwork(hosts, latency_ns=lambda s, d: lat_ms * MS)
    return hosts, net


@pytest.mark.skipif(not os.path.exists(CURL), reason="no curl in image")
def test_curl_byte_verified_transfer():
    hosts, net = two_hosts()
    srv = spawn_native(hosts[0], [HTTPD, "8080", "20000", "1"])
    cli = spawn_native(
        hosts[1], [CURL, "-s", "--no-buffer", "http://10.0.0.1:8080/"],
        start_time=100 * MS,
    )
    net.run(30 * SEC)
    assert srv.exit_code == 0, b"".join(srv.stderr)
    assert cli.exit_code == 0, b"".join(cli.stderr)
    assert b"".join(cli.stdout) == _expected(20000)


@pytest.mark.skipif(not os.path.exists(WGET), reason="no wget in image")
def test_wget_byte_verified_transfer(tmp_path):
    out = str(tmp_path / "wget_out.bin")
    hosts, net = two_hosts()
    srv = spawn_native(hosts[0], [HTTPD, "8080", "50000", "1"])
    # wget peeks response headers with MSG_PEEK before consuming them —
    # a consuming peek desyncs the stream and wget retries then fails
    cli = spawn_native(
        hosts[1], [WGET, "-q", "-O", out, "http://10.0.0.1:8080/f"],
        start_time=100 * MS,
    )
    net.run(30 * SEC)
    assert srv.exit_code == 0, b"".join(srv.stderr)
    assert cli.exit_code == 0, b"".join(cli.stderr)
    with open(out, "rb") as f:
        assert f.read() == _expected(50000)


@pytest.mark.skipif(not os.path.exists(CURL), reason="no curl in image")
def test_curl_transfer_is_deterministic():
    def once():
        hosts, net = two_hosts(seed=21)
        srv = spawn_native(hosts[0], [HTTPD, "8080", "8000", "1"])
        cli = spawn_native(
            hosts[1], [CURL, "-s", "http://10.0.0.1:8080/"],
            start_time=100 * MS,
        )
        net.run(20 * SEC)
        assert cli.exit_code == 0
        return (b"".join(cli.stdout), srv.syscall_count, cli.syscall_count,
                hosts[1].now())

    assert once() == once()


@pytest.mark.skipif(not os.path.exists(CURL), reason="no curl in image")
def test_curl_connection_refused():
    # no server: the SYN is RST'd and curl reports failure (exit 7),
    # proving the refusal path (SO_ERROR after async connect) works
    hosts, net = two_hosts()
    cli = spawn_native(
        hosts[1], [CURL, "-s", "http://10.0.0.1:8080/"], start_time=100 * MS
    )
    net.run(20 * SEC)
    assert cli.exit_code == 7, (cli.exit_code, b"".join(cli.stderr))


DNS_BIN = os.path.join(REPO, "native", "build", "test_dns")


def test_hostname_identity_and_dns():
    """gethostname/uname report the SIMULATED host name; getaddrinfo,
    gethostbyname and getifaddrs answer from the simulator (reference
    shim_api_addrinfo.c / shim_api_ifaddrs.c + dns.c)."""
    hosts, net = two_hosts()
    p = spawn_native(hosts[0], [DNS_BIN, "h1"])
    net.run(2 * SEC)
    assert p.exit_code == 0, b"".join(p.stderr)
    out = b"".join(p.stdout).decode()
    assert "hostname=h0" in out
    assert "nodename=h0 release=6.1.0-shadow" in out
    assert "gai h1 -> 10.0.0.2:80" in out
    assert "gai unknown -> EAI_NONAME" in out
    assert "ghbn h1 -> 10.0.0.2" in out
    assert "if lo 127.0.0.1" in out
    assert "if eth0 10.0.0.1" in out


def test_curl_by_hostname():
    """An unmodified curl resolves a simulated hostname end to end."""
    hosts, net = two_hosts()
    srv = spawn_native(hosts[0], [HTTPD, "8080", "9999", "1"])
    cli = spawn_native(
        hosts[1], [CURL, "-s", "http://h0:8080/x"], start_time=100 * MS
    )
    net.run(30 * SEC)
    assert srv.exit_code == 0 and cli.exit_code == 0, b"".join(cli.stderr)
    assert b"".join(cli.stdout) == _expected(9999)


# --------------------------------------------------------------------------
# multi-threaded server under CONCURRENT load (VERDICT r4 #7). The image
# ships no nginx/busybox, but stock python3's `http.server` module IS a
# ThreadingHTTPServer since 3.7: every connection gets its own OS thread
# (clone + futex under the shim) while three unmodified curl clients hit
# it simultaneously.

PY = "/opt/venv/bin/python3"

THREADED_SERVER = (
    "import http.server, os, threading\n"
    "os.makedirs('{docs}', exist_ok=True)\n"
    "for i in range(3):\n"
    "    open(f'{docs}/f{{i}}.bin', 'wb').write(bytes((i*37+j) % 256\n"
    "        for j in range(30000)))\n"
    "os.chdir('{docs}')\n"
    "class H(http.server.SimpleHTTPRequestHandler):\n"
    "    def log_message(self, fmt, *a):\n"
    "        print('[%s] %s' % (threading.current_thread().name,\n"
    "                           fmt % a), flush=True)\n"
    "srv = http.server.ThreadingHTTPServer(('0.0.0.0', 8000), H)\n"
    "srv.serve_forever()\n"
)


def _threaded_load(tmpdir: str, seed: int = 7):
    docs = os.path.join(tmpdir, "docs")
    hosts = [
        CpuHost(HostConfig(name=f"h{i}", ip=f"10.0.0.{i + 1}", seed=seed,
                           host_id=i))
        for i in range(4)
    ]
    net = CpuNetwork(hosts, latency_ns=lambda s, d: 10 * MS)
    srv = spawn_native(
        hosts[0], [PY, "-c", THREADED_SERVER.format(docs=docs)]
    )
    # three clients fire at the SAME simulated instant: their connections
    # overlap and the server must serve them from three worker threads
    clis = [
        spawn_native(
            hosts[i + 1],
            [CURL, "-s", "--no-buffer", f"http://10.0.0.1:8000/f{i}.bin"],
            start_time=800 * MS,
        )
        for i in range(3)
    ]
    net.run(8 * SEC)
    return srv, clis, hosts


@pytest.mark.skipif(not os.path.exists(PY), reason="no python3 in image")
def test_threaded_httpd_serves_three_concurrent_curls(tmp_path):
    srv, clis, hosts = _threaded_load(str(tmp_path))
    for i, cli in enumerate(clis):
        assert cli.exit_code == 0, b"".join(cli.stderr)[-1500:]
        body = b"".join(cli.stdout)
        assert body == bytes((i * 37 + j) % 256 for j in range(30000)), (
            f"client {i}: got {len(body)} bytes"
        )
    assert srv.state == "running"  # daemon alive at horizon
    # the requests really ran on DISTINCT worker threads of one server
    log = b"".join(srv.stdout).decode()
    thread_names = {
        line.split("]")[0].strip("[")
        for line in log.splitlines()
        if line.startswith("[Thread-")
    }
    assert len(thread_names) == 3, log


@pytest.mark.skipif(not os.path.exists(PY), reason="no python3 in image")
def test_threaded_httpd_deterministic_reruns(tmp_path):
    def once(i):
        srv, clis, hosts = _threaded_load(str(tmp_path / f"r{i}"), seed=13)
        return (
            tuple(b"".join(c.stdout) for c in clis),
            tuple(c.exit_code for c in clis),
            tuple(h.counters["pkts_recv"] for h in hosts),
            tuple(h.counters["syscalls"] for h in hosts),
        )

    a, b = once(0), once(1)
    assert a == b
    assert a[1] == (0, 0, 0)
