"""Unmodified third-party binaries under the shim (the reference proves
itself on stock applications: examples/apps curl/wget/nginx/... — here the
distro's /usr/bin/curl and /usr/bin/wget complete byte-verified HTTP
transfers over the simulated network against a purpose-written server)."""

from __future__ import annotations

import os

import pytest

from shadow_tpu.host import CpuHost, HostConfig
from shadow_tpu.host.network import CpuNetwork

pytestmark = pytest.mark.skipif(
    not __import__("shadow_tpu.native_plane", fromlist=["ensure_built"]).ensure_built(),
    reason="native toolchain unavailable",
)

from shadow_tpu.native_plane import spawn_native  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
HTTPD = os.path.join(REPO, "native", "build", "test_httpd")
CURL = "/usr/bin/curl"
WGET = "/usr/bin/wget"

MS = 1_000_000
SEC = 1_000_000_000


def _expected(n: int) -> bytes:
    block = bytes(ord("A") + (i % 26) for i in range(4096))
    return (block * (n // 4096 + 1))[:n]


def two_hosts(lat_ms=10, seed=7):
    hosts = [
        CpuHost(HostConfig(name=f"h{i}", ip=f"10.0.0.{i + 1}", seed=seed, host_id=i))
        for i in range(2)
    ]
    net = CpuNetwork(hosts, latency_ns=lambda s, d: lat_ms * MS)
    return hosts, net


@pytest.mark.skipif(not os.path.exists(CURL), reason="no curl in image")
def test_curl_byte_verified_transfer():
    hosts, net = two_hosts()
    srv = spawn_native(hosts[0], [HTTPD, "8080", "20000", "1"])
    cli = spawn_native(
        hosts[1], [CURL, "-s", "--no-buffer", "http://10.0.0.1:8080/"],
        start_time=100 * MS,
    )
    net.run(30 * SEC)
    assert srv.exit_code == 0, b"".join(srv.stderr)
    assert cli.exit_code == 0, b"".join(cli.stderr)
    assert b"".join(cli.stdout) == _expected(20000)


@pytest.mark.skipif(not os.path.exists(WGET), reason="no wget in image")
def test_wget_byte_verified_transfer(tmp_path):
    out = str(tmp_path / "wget_out.bin")
    hosts, net = two_hosts()
    srv = spawn_native(hosts[0], [HTTPD, "8080", "50000", "1"])
    # wget peeks response headers with MSG_PEEK before consuming them —
    # a consuming peek desyncs the stream and wget retries then fails
    cli = spawn_native(
        hosts[1], [WGET, "-q", "-O", out, "http://10.0.0.1:8080/f"],
        start_time=100 * MS,
    )
    net.run(30 * SEC)
    assert srv.exit_code == 0, b"".join(srv.stderr)
    assert cli.exit_code == 0, b"".join(cli.stderr)
    with open(out, "rb") as f:
        assert f.read() == _expected(50000)


@pytest.mark.skipif(not os.path.exists(CURL), reason="no curl in image")
def test_curl_transfer_is_deterministic():
    def once():
        hosts, net = two_hosts(seed=21)
        srv = spawn_native(hosts[0], [HTTPD, "8080", "8000", "1"])
        cli = spawn_native(
            hosts[1], [CURL, "-s", "http://10.0.0.1:8080/"],
            start_time=100 * MS,
        )
        net.run(20 * SEC)
        assert cli.exit_code == 0
        return (b"".join(cli.stdout), srv.syscall_count, cli.syscall_count,
                hosts[1].now())

    assert once() == once()


@pytest.mark.skipif(not os.path.exists(CURL), reason="no curl in image")
def test_curl_connection_refused():
    # no server: the SYN is RST'd and curl reports failure (exit 7),
    # proving the refusal path (SO_ERROR after async connect) works
    hosts, net = two_hosts()
    cli = spawn_native(
        hosts[1], [CURL, "-s", "http://10.0.0.1:8080/"], start_time=100 * MS
    )
    net.run(20 * SEC)
    assert cli.exit_code == 7, (cli.exit_code, b"".join(cli.stderr))


DNS_BIN = os.path.join(REPO, "native", "build", "test_dns")


def test_hostname_identity_and_dns():
    """gethostname/uname report the SIMULATED host name; getaddrinfo,
    gethostbyname and getifaddrs answer from the simulator (reference
    shim_api_addrinfo.c / shim_api_ifaddrs.c + dns.c)."""
    hosts, net = two_hosts()
    p = spawn_native(hosts[0], [DNS_BIN, "h1"])
    net.run(2 * SEC)
    assert p.exit_code == 0, b"".join(p.stderr)
    out = b"".join(p.stdout).decode()
    assert "hostname=h0" in out
    assert "nodename=h0 release=6.1.0-shadow" in out
    assert "gai h1 -> 10.0.0.2:80" in out
    assert "gai unknown -> EAI_NONAME" in out
    assert "ghbn h1 -> 10.0.0.2" in out
    assert "if lo 127.0.0.1" in out
    assert "if eth0 10.0.0.1" in out


def test_curl_by_hostname():
    """An unmodified curl resolves a simulated hostname end to end."""
    hosts, net = two_hosts()
    srv = spawn_native(hosts[0], [HTTPD, "8080", "9999", "1"])
    cli = spawn_native(
        hosts[1], [CURL, "-s", "http://h0:8080/x"], start_time=100 * MS
    )
    net.run(30 * SEC)
    assert srv.exit_code == 0 and cli.exit_code == 0, b"".join(cli.stderr)
    assert b"".join(cli.stdout) == _expected(9999)
