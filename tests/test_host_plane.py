"""Host-emulation plane tests (reference test families: pipe, eventfd,
timerfd, epoll, udp, tcp, sockets — SURVEY.md §4.2)."""

from __future__ import annotations

import pytest

from shadow_tpu.host import (
    CpuHost,
    EventFd,
    FileState,
    HostConfig,
    create_pipe,
)
from shadow_tpu.host.network import CpuNetwork

MS = 1_000_000
SEC = 1_000_000_000


def make_hosts(n, *, lat_ns=10 * MS, loss=0.0, seed=1):
    hosts = [
        CpuHost(HostConfig(name=f"h{i}", ip=f"10.0.0.{i + 1}", seed=seed, host_id=i))
        for i in range(n)
    ]
    net = CpuNetwork(
        hosts,
        latency_ns=lambda s, d: lat_ns,
        loss=(lambda s, d: loss) if loss else None,
    )
    return hosts, net


# ------------------------------------------------------------------- pipes


def test_pipe_roundtrip_and_eof():
    r, w = create_pipe()
    assert r.read(10) is None  # empty: would block
    assert w.write(b"hello") == 5
    assert r.state & FileState.READABLE
    assert r.read(3) == b"hel"
    assert r.read(10) == b"lo"
    assert not (r.state & FileState.READABLE)
    w.close()
    assert r.read(10) == b""  # EOF
    assert r.state & FileState.HUP


def test_pipe_fills_and_blocks():
    r, w = create_pipe(capacity=10)
    assert w.write(b"x" * 20) == 10
    assert w.write(b"y") is None  # full
    assert not (w.state & FileState.WRITABLE)
    r.read(4)
    # pipe(7): POLLOUT requires min(PIPE_BUF, capacity) free, not any byte
    assert not (w.state & FileState.WRITABLE)
    r.read(16)  # drained: full capacity free again
    assert w.state & FileState.WRITABLE


def test_pipe_epipe_when_reader_closes():
    r, w = create_pipe()
    r.close()
    with pytest.raises(BrokenPipeError):
        w.write(b"data")


# ----------------------------------------------------------------- eventfd


def test_eventfd_counter_and_semaphore():
    e = EventFd(0)
    assert e.read(8) is None
    e.write((3).to_bytes(8, "little"))
    e.write((4).to_bytes(8, "little"))
    assert int.from_bytes(e.read(8), "little") == 7
    assert e.read(8) is None
    s = EventFd(2, semaphore=True)
    assert int.from_bytes(s.read(8), "little") == 1
    assert int.from_bytes(s.read(8), "little") == 1
    assert s.read(8) is None


# ------------------------------------------------------- program scheduling


def test_nanosleep_and_clock():
    (h,), _ = make_hosts(1)
    times = []

    def prog(ctx):
        t0 = yield ("clock_gettime",)
        times.append(t0)
        yield ("nanosleep", 250 * MS)
        t1 = yield ("clock_gettime",)
        times.append(t1)

    h.spawn(prog)
    h.execute(1 * SEC)
    assert times == [0, 250 * MS]


def test_timerfd_periodic_via_epoll():
    (h,), _ = make_hosts(1)
    fired = []

    def prog(ctx):
        tfd = yield ("timerfd_create",)
        ep = yield ("epoll_create",)
        yield ("epoll_ctl", ep, "add", tfd, 0x001)  # EPOLLIN
        yield ("timerfd_settime", tfd, 100 * MS, 100 * MS)
        for _ in range(3):
            evs = yield ("epoll_wait", ep)
            assert evs
            n = yield ("read", tfd, 8)
            now = yield ("clock_gettime",)
            fired.append((now, int.from_bytes(n, "little")))
        yield ("exit", 0)

    h.spawn(prog)
    h.execute(1 * SEC)
    assert fired == [(100 * MS, 1), (200 * MS, 1), (300 * MS, 1)]


def test_pipe_between_processes_blocks_and_wakes():
    (h,), _ = make_hosts(1)
    out = []

    def writer_reader(ctx):
        rfd, wfd = yield ("pipe",)
        # child-style second process shares the pipe through the host: spawn
        # a reader program bound to the same fds via the handler
        data = b"ping"
        yield ("nanosleep", 50 * MS)
        yield ("write", wfd, data)
        yield ("nanosleep", 50 * MS)
        out.append("writer done")

    h.spawn(writer_reader)
    h.execute(1 * SEC)
    assert out == ["writer done"]


# ---------------------------------------------------------------- udp e2e


def test_udp_echo_between_hosts():
    hosts, net = make_hosts(2)
    server_log, client_log = [], []

    def server(ctx):
        fd = yield ("socket", "udp")
        yield ("bind", fd, ("0.0.0.0", 9000))
        while True:
            data, addr = yield ("recvfrom", fd, 2048)
            server_log.append(data)
            yield ("sendto", fd, data.upper(), addr)

    def client(ctx):
        fd = yield ("socket", "udp")
        yield ("connect", fd, ("10.0.0.1", 9000))
        yield ("sendto", fd, b"hello")
        data, _ = yield ("recvfrom", fd, 2048)
        client_log.append((data, (yield ("clock_gettime",))))
        yield ("exit", 0)

    hosts[0].spawn(server)
    hosts[1].spawn(client)
    net.run(1 * SEC)
    assert server_log == [b"hello"]
    assert client_log == [(b"HELLO", 20 * MS)]  # 2 x 10ms RTT


def test_udp_unreachable_is_dropped():
    hosts, net = make_hosts(2)

    def client(ctx):
        fd = yield ("socket", "udp")
        yield ("sendto", fd, b"void", ("10.9.9.9", 1234))
        yield ("exit", 0)

    hosts[1].spawn(client)
    net.run(1 * SEC)
    assert net.pkts_relayed == 0


# ---------------------------------------------------------------- tcp e2e


def test_tcp_connect_transfer_close():
    hosts, net = make_hosts(2)
    got = []
    accepted = []

    def server(ctx):
        fd = yield ("socket", "tcp")
        yield ("bind", fd, ("0.0.0.0", 80))
        yield ("listen", fd)
        cfd, peer = yield ("accept", fd)
        accepted.append(peer)
        buf = bytearray()
        while True:
            data = yield ("recv", cfd, 4096)
            if data == b"":
                break
            buf.extend(data)
        got.append(bytes(buf))
        yield ("close", cfd)
        yield ("exit", 0)

    payload = bytes(range(256)) * 2000  # 512 KB

    def client(ctx):
        fd = yield ("socket", "tcp")
        yield ("connect", fd, ("10.0.0.1", 80))
        sent = 0
        while sent < len(payload):
            n = yield ("send", fd, payload[sent : sent + 32768])
            sent += n
        yield ("shutdown", fd)
        yield ("exit", 0)

    hosts[0].spawn(server)
    hosts[1].spawn(client)
    net.run(30 * SEC)
    assert got == [payload]
    assert accepted and accepted[0][0] == "10.0.0.2"


def test_tcp_connection_refused():
    hosts, net = make_hosts(2)
    errors = []

    def client(ctx):
        fd = yield ("socket", "tcp")
        try:
            yield ("connect", fd, ("10.0.0.1", 81))  # nothing listens
        except OSError as e:
            errors.append(str(e))
        yield ("exit", 0)

    hosts[1].spawn(client)
    net.run(5 * SEC)
    assert errors and "refused" in errors[0]


def test_tcp_transfer_with_loss():
    hosts, net = make_hosts(2, loss=0.05)
    got = []

    def server(ctx):
        fd = yield ("socket", "tcp")
        yield ("bind", fd, ("0.0.0.0", 80))
        yield ("listen", fd)
        cfd, _ = yield ("accept", fd)
        buf = bytearray()
        while (data := (yield ("recv", cfd, 8192))) != b"":
            buf.extend(data)
        got.append(bytes(buf))
        yield ("exit", 0)

    payload = bytes(range(251)) * 400  # ~100KB, prime-ish pattern

    def client(ctx):
        fd = yield ("socket", "tcp")
        yield ("connect", fd, ("10.0.0.1", 80))
        sent = 0
        while sent < len(payload):
            sent += yield ("send", fd, payload[sent : sent + 16384])
        yield ("shutdown", fd)
        yield ("exit", 0)

    hosts[0].spawn(server)
    hosts[1].spawn(client)
    net.run(120 * SEC)
    assert got == [payload]
    assert net.pkts_dropped > 0


# ----------------------------------------------------------- determinism


def test_host_plane_determinism():
    """Identical config twice => identical stdout + counters (the host-plane
    face of the reference determinism suite, src/test/determinism/)."""

    def once():
        hosts, net = make_hosts(3, loss=0.02, seed=42)
        logs = []

        def server(ctx):
            fd = yield ("socket", "udp")
            yield ("bind", fd, ("0.0.0.0", 7))
            while True:
                data, addr = yield ("recvfrom", fd, 1024)
                yield ("sendto", fd, data, addr)

        def client(ctx):
            fd = yield ("socket", "udp")
            yield ("connect", fd, ("10.0.0.1", 7))
            for i in range(20):
                yield ("sendto", fd, f"m{i}".encode())
                yield ("nanosleep", 30 * MS)
            yield ("exit", 0)

        hosts[0].spawn(server)
        hosts[1].spawn(client)
        hosts[2].spawn(client)
        net.run(3 * SEC)
        return (
            [h.counters for h in hosts],
            net.pkts_dropped,
            net.pkts_relayed,
        )

    assert once() == once()


def test_syscall_counters_and_strace():
    (h,), _ = make_hosts(1)
    trace = []

    def prog(ctx):
        yield ("write_stdout", b"hi\n")
        yield ("nanosleep", MS)
        yield ("exit", 0)

    p = h.spawn(prog)
    p.strace = lambda t, pid, name, args, res: trace.append((t, name))
    h.execute(1 * SEC)
    assert [n for _, n in trace] == ["write_stdout", "nanosleep", "exit"]
    assert p.exit_code == 0
    assert p.stdout == [b"hi\n"]
