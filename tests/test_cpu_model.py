"""Device-side CPU delay model tests (reference host/cpu.rs +
host.rs:820-847 CPU-delay event rescheduling)."""

from __future__ import annotations

from shadow_tpu.config.options import ConfigOptions
from shadow_tpu.sim import Simulation

MS = 1_000_000


def _cfg(cpu_delay: str | int = 0):
    return ConfigOptions.from_dict(
        {
            "general": {"stop_time": "2 s", "seed": 3},
            "network": {"graph": {"type": "1_gbit_switch"}},
            "experimental": {"cpu_delay": cpu_delay},
            "hosts": {
                "n": {
                    "count": 8,
                    "network_node_id": 0,
                    "processes": [
                        {"model": "timer", "model_args": {"interval": "100 ms"}}
                    ],
                }
            },
        }
    )


def test_cpu_delay_off_by_default_and_deterministic():
    a = Simulation(_cfg(), world=1)
    a.run(progress=False)
    b = Simulation(_cfg(), world=1)
    b.run(progress=False)
    assert (
        a.stats_report()["determinism_digest"]
        == b.stats_report()["determinism_digest"]
    )


def test_cpu_delay_below_event_spacing_is_invisible():
    """A CPU charge smaller than the event spacing never defers anything:
    the run is bit-identical to the delay-free one (the reference's CPU
    model likewise only bites when the CPU is still busy at pop time)."""
    base = Simulation(_cfg(0), world=1)
    base.run(progress=False)
    delayed = Simulation(_cfg("1 ms"), world=1)
    delayed.run(progress=False)
    rb = base.stats_report()
    rd = delayed.stats_report()
    assert rd["events_processed"] == rb["events_processed"]
    assert rd["determinism_digest"] == rb["determinism_digest"]


def test_cpu_delay_throttles_dense_events():
    """A CPU delay LARGER than the event spacing must throttle execution:
    fewer events fit in the simulated horizon (the busy CPU pushes work
    past stop_time), exactly the reference's busy-CPU deferral."""
    base = Simulation(_cfg(0), world=1)
    base.run(progress=False)
    slow = Simulation(_cfg("300 ms"), world=1)  # 3x the timer interval
    slow.run(progress=False)
    assert (
        slow.stats_report()["events_processed"]
        < base.stats_report()["events_processed"]
    )
