"""Circuit model (Tor-like 3-hop relay chains, BASELINE config 4 workload;
reference: src/test/tor/minimal).

The compiled-`Simulation` legs run in subprocesses via tests/subproc.py:
this box's jaxlib intermittently heap-corrupts inside compiled Simulation
runs (rc 134/139 with no output), and an in-process abort would take the
whole pytest run down with it."""

from __future__ import annotations

import pytest

from shadow_tpu.sim import Simulation
from tests.subproc import run_isolated_json

_CFG_SRC = '''
def _cfg(n_relays=6, n_clients=4, stop="5 s", seed=11, sched="tpu"):
    from shadow_tpu.config.options import ConfigOptions

    return ConfigOptions.from_dict(
        {
            "general": {"stop_time": stop, "seed": seed},
            "network": {
                "graph": {
                    "type": "gml",
                    "inline": """
graph [ directed 0
  node [ id 0 host_bandwidth_down "1 Gbit" host_bandwidth_up "1 Gbit" ]
  edge [ source 0 target 0 latency "20 ms" ]
]""",
                }
            },
            "experimental": {"scheduler": sched},
            "hosts": {
                "relay": {
                    "count": n_relays,
                    "network_node_id": 0,
                    "processes": [{"model": "circuit",
                                   "model_args": {"role": "relay"}}],
                },
                "cli": {
                    "count": n_clients,
                    "network_node_id": 0,
                    "processes": [{"model": "circuit",
                                   "model_args": {"role": "client",
                                                  "interval": "500 ms"}}],
                },
            },
        }
    )
'''


def _cfg(n_relays=6, n_clients=4, stop="5 s", seed=11, sched="tpu"):
    ns: dict = {}
    exec(_CFG_SRC, ns)  # one config source for in- and out-of-process legs
    return ns["_cfg"](n_relays, n_clients, stop, seed, sched)


def test_cells_complete_round_trips():
    out = run_isolated_json(_CFG_SRC + '''
import json
from shadow_tpu.sim import Simulation

r = Simulation(_cfg(), world=1).run(progress=False)
print(json.dumps(r))
''')
    m = out["model_report"]
    assert m["cells_completed"] > 0
    # 6 wire hops per completed cell (3 out + 3 back)
    assert out["packets_delivered"] >= m["cells_completed"] * 6
    # RTT >= 6 x 20 ms wire + 5 relay processing delays (2 ms each)
    assert m["mean_rtt_ms"] >= 6 * 20 + 5 * 2 - 1
    # every forward was charged a processing delay first
    assert m["relay_forwards"] >= m["cells_completed"] * 5


def test_matches_golden_oracle():
    out = run_isolated_json(_CFG_SRC + '''
import json
from shadow_tpu.sim import Simulation

dev = Simulation(_cfg(seed=3), world=1).run(progress=False)
gold = Simulation(_cfg(seed=3, sched="cpu-reference"), world=1).run(
    progress=False
)
print(json.dumps({"dev": dev, "gold": gold}))
''')
    dev, gold = out["dev"], out["gold"]
    assert dev["determinism_digest"] == gold["determinism_digest"]
    assert dev["model_report"] == gold["model_report"]


def test_mesh_invariant():
    out = run_isolated_json(_CFG_SRC + '''
import json
from shadow_tpu.sim import Simulation

a = Simulation(_cfg(seed=5), world=1).run(progress=False)
b = Simulation(_cfg(seed=5), world=8).run(progress=False)
print(json.dumps({"a": a, "b": b}))
''')
    a, b = out["a"], out["b"]
    assert a["determinism_digest"] == b["determinism_digest"]
    assert a["model_report"] == b["model_report"]


def test_needs_three_relays():
    # config validation raises before any compiled chunk runs: in-process
    with pytest.raises(Exception, match="3 relay"):
        Simulation(_cfg(n_relays=2), world=1)
