"""Event-queue kernel unit tests (reference analogue:
src/main/core/work/event_queue.rs tests + event.rs ordering tests)."""

import jax.numpy as jnp
import numpy as np

from shadow_tpu.ops import (
    make_queue,
    next_time,
    pop_min,
    push_one,
    pack_order,
    queue_len,
    merge_flat_events,
)
from shadow_tpu.ops.events import EVENT_PAYLOAD_WORDS
from shadow_tpu.simtime import TIME_MAX

H, C = 4, 8


def _push(q, host, t, order, kind=1, payload=None):
    mask = jnp.arange(H) == host
    tt = jnp.full((H,), t, jnp.int64)
    oo = jnp.full((H,), order, jnp.int64)
    kk = jnp.full((H,), kind, jnp.int32)
    pp = jnp.zeros((H, EVENT_PAYLOAD_WORDS), jnp.int32)
    if payload is not None:
        pp = pp + jnp.asarray(payload, jnp.int32)[None, :]
    return push_one(q, mask, tt, oo, kk, pp)


def test_push_pop_roundtrip():
    q = make_queue(H, C)
    q = _push(q, 0, 100, 5)
    q = _push(q, 0, 50, 7)
    q = _push(q, 2, 10, 1)
    nt = np.asarray(next_time(q))
    assert nt[0] == 50 and nt[2] == 10 and nt[1] == TIME_MAX

    q, ev, active = pop_min(q, TIME_MAX)
    assert list(np.asarray(active)) == [True, False, True, False]
    assert np.asarray(ev.t)[0] == 50
    assert np.asarray(ev.t)[2] == 10

    q, ev, active = pop_min(q, TIME_MAX)
    assert np.asarray(ev.t)[0] == 100
    assert not np.asarray(active)[2]


def test_pop_respects_limit():
    q = make_queue(H, C)
    q = _push(q, 1, 500, 1)
    q, ev, active = pop_min(q, 500)  # strictly-before semantics
    assert not np.asarray(active)[1]
    q, ev, active = pop_min(q, 501)
    assert np.asarray(active)[1]


def test_deterministic_tiebreak_packets_before_local():
    """Equal times: packets (is_local=0) pop before local tasks, then by
    (src, seq) — the event.rs:102-155 total order."""
    q = make_queue(H, C)
    q = _push(q, 0, 100, pack_order(1, 0, 3))  # local task
    q = _push(q, 0, 100, pack_order(0, 2, 9))  # packet from host 2
    q = _push(q, 0, 100, pack_order(0, 1, 11))  # packet from host 1
    q, ev, _ = pop_min(q, TIME_MAX)
    assert np.asarray(ev.order)[0] == int(pack_order(0, 1, 11))
    q, ev, _ = pop_min(q, TIME_MAX)
    assert np.asarray(ev.order)[0] == int(pack_order(0, 2, 9))
    q, ev, _ = pop_min(q, TIME_MAX)
    assert np.asarray(ev.order)[0] == int(pack_order(1, 0, 3))


def test_overflow_counts_dropped():
    q = make_queue(2, 2)
    mask = jnp.array([True, False])
    t = jnp.zeros((2,), jnp.int64)
    o = jnp.zeros((2,), jnp.int64)
    k = jnp.zeros((2,), jnp.int32)
    p = jnp.zeros((2, EVENT_PAYLOAD_WORDS), jnp.int32)
    for i in range(3):
        q = push_one(q, mask, t + i, o + i, k, p)
    assert int(q.dropped[0]) == 1
    assert int(queue_len(q)[0]) == 2


def test_merge_flat_events_sorted_and_counted():
    q = make_queue(H, C)
    q = _push(q, 1, 5, 1)  # pre-existing event occupies slot 0 of host 1
    n = 6
    dst = jnp.array([1, 1, 3, 1, 0, 2], jnp.int32)
    t = jnp.array([30, 10, 7, 20, 9, 9], jnp.int64)
    order = jnp.array([pack_order(0, s, i) for i, s in enumerate([2, 3, 0, 1, 1, 1])], jnp.int64)
    kind = jnp.full((n,), 2, jnp.int32)
    payload = jnp.tile(jnp.arange(n, dtype=jnp.int32)[:, None], (1, EVENT_PAYLOAD_WORDS))
    valid = jnp.array([True, True, True, True, False, True])

    q2 = merge_flat_events(q, dst, t, order, kind, payload, valid, max_inserts=C)
    assert int(queue_len(q2)[1]) == 4  # 1 old + 3 merged
    assert int(queue_len(q2)[0]) == 0  # invalid entry not inserted
    assert int(queue_len(q2)[2]) == 1
    assert int(queue_len(q2)[3]) == 1

    # pop host 1 in order: 5 (old), then 10/20/30 by time
    times = []
    for _ in range(4):
        q2, ev, active = pop_min(q2, TIME_MAX)
        assert np.asarray(active)[1]
        times.append(int(np.asarray(ev.t)[1]))
    assert times == [5, 10, 20, 30]


def test_merge_overflow_sheds_latest_not_earliest():
    """Under overflow pressure the merge must keep the most urgent events:
    drop priority is (time, order), not the raw order key."""
    q = make_queue(1, 1)
    dst = jnp.zeros((2,), jnp.int32)
    t = jnp.array([100, 5], jnp.int64)
    # the later event has the *smaller* order key (earlier src)
    order = jnp.array([pack_order(0, 0, 1), pack_order(0, 3, 1)], jnp.int64)
    kind = jnp.zeros((2,), jnp.int32)
    payload = jnp.zeros((2, EVENT_PAYLOAD_WORDS), jnp.int32)
    q2 = merge_flat_events(q, dst, t, order, kind, payload, jnp.ones((2,), bool), 4)
    q2, ev, active = pop_min(q2, TIME_MAX)
    assert int(np.asarray(ev.t)[0]) == 5  # urgent event survived
    assert int(q2.dropped[0]) == 1


def test_merge_overflow_drops_counted():
    q = make_queue(2, 2)
    n = 4
    dst = jnp.zeros((n,), jnp.int32)
    t = jnp.arange(n, dtype=jnp.int64) + 1
    order = jnp.array([pack_order(0, 0, i) for i in range(n)], jnp.int64)
    kind = jnp.zeros((n,), jnp.int32)
    payload = jnp.zeros((n, EVENT_PAYLOAD_WORDS), jnp.int32)
    valid = jnp.ones((n,), bool)
    q2 = merge_flat_events(q, dst, t, order, kind, payload, valid, max_inserts=8)
    assert int(queue_len(q2)[0]) == 2
    assert int(q2.dropped[0]) == 2
    # lowest-order entries won the slots
    q2, ev, _ = pop_min(q2, TIME_MAX)
    assert int(np.asarray(ev.t)[0]) == 1


def test_merge_gather_and_scatter_paths_agree():
    """The TPU (token-sort + gather) and CPU (scatter) insertion paths must
    produce bit-identical queues for any input, including overflow — the
    bench's vs_baseline comparison and cross-platform digest stability both
    rest on this."""
    rng = np.random.default_rng(7)
    for trial in range(8):
        hh, cc = int(rng.integers(1, 12)), int(rng.integers(1, 6))
        n = int(rng.integers(1, 40))
        q = make_queue(hh, cc)
        # pre-occupy random slots
        occ = rng.random((hh, cc)) < 0.4
        qt = np.where(occ, rng.integers(1, 1000, (hh, cc)), np.asarray(q.t))
        qo = np.where(
            occ,
            rng.integers(0, 1 << 40, (hh, cc)),
            np.asarray(q.order),
        )
        q = q._replace(t=jnp.asarray(qt), order=jnp.asarray(qo))
        dst = jnp.asarray(rng.integers(0, hh, n), jnp.int32)
        t = jnp.asarray(rng.integers(1, 1000, n), jnp.int64)
        order = jnp.asarray(
            [int(pack_order(0, int(rng.integers(0, hh)), 1000 + i)) for i in range(n)],
            jnp.int64,
        )
        kind = jnp.asarray(rng.integers(0, 5, n), jnp.int32)
        payload = jnp.asarray(
            rng.integers(0, 100, (n, EVENT_PAYLOAD_WORDS)), jnp.int32
        )
        valid = jnp.asarray(rng.random(n) < 0.8)
        for shed in (True, False):
            a = merge_flat_events(
                q, dst, t, order, kind, payload, valid, max_inserts=cc,
                shed_urgency=shed, force_path="gather",
            )
            b = merge_flat_events(
                q, dst, t, order, kind, payload, valid, max_inserts=cc,
                shed_urgency=shed, force_path="scatter",
            )
            for fa, fb, name in zip(a, b, a._fields):
                assert np.array_equal(np.asarray(fa), np.asarray(fb)), (
                    f"trial {trial} shed={shed} field {name}"
                )


def test_merge_rows_truncation_exact_when_sized():
    """merge_rows >= valid + H + 1 must change nothing (gather path)."""
    rng = np.random.default_rng(11)
    hh, cc, n = 6, 4, 30
    q = make_queue(hh, cc)
    dst = jnp.asarray(rng.integers(0, hh, n), jnp.int32)
    t = jnp.asarray(rng.integers(1, 1000, n), jnp.int64)
    order = jnp.asarray(
        [int(pack_order(0, int(rng.integers(0, hh)), i)) for i in range(n)],
        jnp.int64,
    )
    kind = jnp.asarray(rng.integers(0, 5, n), jnp.int32)
    payload = jnp.asarray(
        rng.integers(0, 100, (n, EVENT_PAYLOAD_WORDS)), jnp.int32
    )
    valid = jnp.asarray(rng.random(n) < 0.7)
    a = merge_flat_events(
        q, dst, t, order, kind, payload, valid, max_inserts=cc,
        force_path="gather",
    )
    b = merge_flat_events(
        q, dst, t, order, kind, payload, valid, max_inserts=cc,
        force_path="gather", merge_rows=n + hh + 1,
    )
    for fa, fb, name in zip(a, b, a._fields):
        assert np.array_equal(np.asarray(fa), np.asarray(fb)), name


def test_merge_rows_truncation_sheds_counted():
    """An undersized merge_rows sheds by sorted position — and every shed
    event lands in `dropped`, never silently."""
    hh, cc = 3, 4
    q = make_queue(hh, cc)
    n = 9
    dst = jnp.asarray([0, 0, 0, 1, 1, 1, 2, 2, 2], jnp.int32)
    t = jnp.arange(1, n + 1, dtype=jnp.int64)
    order = jnp.asarray([int(pack_order(0, 0, i)) for i in range(n)], jnp.int64)
    kind = jnp.zeros((n,), jnp.int32)
    payload = jnp.zeros((n, EVENT_PAYLOAD_WORDS), jnp.int32)
    valid = jnp.ones((n,), bool)
    # full run inserts all 9
    full = merge_flat_events(
        q, dst, t, order, kind, payload, valid, max_inserts=cc,
        force_path="gather",
    )
    assert int(np.asarray(queue_len(full)).sum()) == 9
    assert int(np.asarray(full.dropped).sum()) == 0
    # sorted layout: [tok0, e0, e1, e2, tok1, e3, e4, e5, tok2, e6, e7, e8,
    # tok3]; merge_rows=7 keeps positions < 7 -> host 0 whole, host 1 only
    # its first entry (position 5, 6 -> e3 at 5... entries at 5,6 = e3, e4)
    cut = merge_flat_events(
        q, dst, t, order, kind, payload, valid, max_inserts=cc,
        force_path="gather", merge_rows=7,
    )
    kept = int(np.asarray(queue_len(cut)).sum())
    shed = int(np.asarray(cut.dropped).sum())
    assert kept + shed == 9
    assert kept == 5  # host0: 3, host1: 2 (positions 5, 6), host2: 0
    # host 0 intact, host 2 fully shed
    assert int(np.asarray(queue_len(cut))[0]) == 3
    assert int(np.asarray(queue_len(cut))[2]) == 0


def test_merge_rows_truncation_paths_agree():
    """merge_rows sheds must be bit-identical between the gather path and
    the scatter path (the scatter side mirrors the token-interleaved
    positional cut) — cross-backend digest stability in the shed regime."""
    rng = np.random.default_rng(23)
    for trial in range(6):
        hh, cc = int(rng.integers(2, 10)), int(rng.integers(2, 6))
        n = int(rng.integers(4, 40))
        mr = int(rng.integers(2, n + hh + 2))
        q = make_queue(hh, cc)
        dst = jnp.asarray(rng.integers(0, hh, n), jnp.int32)
        t = jnp.asarray(rng.integers(1, 1000, n), jnp.int64)
        order = jnp.asarray(
            [int(pack_order(0, int(rng.integers(0, hh)), 50 + i)) for i in range(n)],
            jnp.int64,
        )
        kind = jnp.asarray(rng.integers(0, 5, n), jnp.int32)
        payload = jnp.asarray(
            rng.integers(0, 100, (n, EVENT_PAYLOAD_WORDS)), jnp.int32
        )
        valid = jnp.asarray(rng.random(n) < 0.8)
        for shed in (True, False):
            a = merge_flat_events(
                q, dst, t, order, kind, payload, valid, max_inserts=cc,
                shed_urgency=shed, force_path="gather", merge_rows=mr,
            )
            b = merge_flat_events(
                q, dst, t, order, kind, payload, valid, max_inserts=cc,
                shed_urgency=shed, force_path="scatter", merge_rows=mr,
            )
            for fa, fb, name in zip(a, b, a._fields):
                assert np.array_equal(np.asarray(fa), np.asarray(fb)), (
                    f"trial {trial} shed={shed} mr={mr} field {name}"
                )
