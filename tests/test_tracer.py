"""Device-resident round tracer (`observability.trace`, PR 3): the
zero-interference gate plus export validity.

The tracer's contract mirrors the queue/pop PRs' bit-identity contracts:
  1. enabling the trace ring changes NOTHING observable — digests,
     per-host event counts, and every drop counter are bit-identical to
     the untraced run, across echo/phold/tgen, flat and bucketed queue
     layouts, K in {1, 4} (the ISSUE acceptance matrix);
  2. the ring records exactly `stats.rounds` rows with monotone round
     indices and strictly increasing window starts, and its per-round
     counters reconcile with the engine's cumulative counters;
  3. the exported Chrome trace is valid JSON with one canonical round
     record per completed round, and `tools/trace_summary.py` (stdlib-
     only) consumes it;
  4. a ring smaller than the inter-drain round count loses the OLDEST
     rows and counts them — never silently.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import jax
import numpy as np
import pytest

from shadow_tpu.core import Engine
from shadow_tpu.obs.tracer import (
    COL_EVENTS,
    COL_MICROSTEPS,
    COL_NEXT_TIME,
    COL_OCC_HWM,
    COL_ROUND,
    COL_WINDOW_END,
    COL_WINDOW_START,
    RoundTracer,
    TRACE_FIELDS,
)
from tests.engine_harness import build_sim, mk_hosts

RING = 64  # matches the harness rounds_per_chunk: a drain per chunk never wraps


def _run(model, hosts, stop, *, k=1, qb=0, trace=False, ring=RING, **kw):
    cfg, m, params, mstate, events = build_sim(
        model, hosts, stop, world=1, queue_block=qb, microstep_events=k,
        trace_rounds=(ring if trace else 0), **kw
    )
    eng = Engine(cfg, m, None)
    state, params = eng.init_state(params, mstate, events, seed=1)
    tracer = RoundTracer(ring) if trace else None
    chunks = 0
    while not bool(state.done):
        t0 = time.monotonic()
        state = eng.run_chunk(state, params)
        if tracer is not None:
            jax.block_until_ready(state)
            tracer.drain(state.trace, wall_t0=t0, wall_t1=time.monotonic())
        chunks += 1
        assert chunks < 500
    return state, tracer


# short-horizon variants of test_popk's workload trio: enough rounds to
# exercise exchange/merge/defer paths, small enough for 24 jit builds
_CASES = {
    "phold": ("phold", mk_hosts(8, {"mean_delay": "20 ms", "population": 3}),
              300_000_000, dict(loss=0.1)),
    "echo": ("udp_echo",
             [dict(host_id=0, name="server", start_time=0,
                   model_args={"role": "server"})]
             + [dict(host_id=i, name=f"c{i}", start_time=0,
                     model_args={"role": "client", "peer": "server",
                                 "interval": "4 ms", "size_bytes": 2000})
                for i in range(1, 5)],
             200_000_000, dict(bw_bits=2_000_000, loss=0.05)),
    "tgen": ("tgen_tcp",
             mk_hosts(5, {"flow_segs": 8, "flows": 1, "cwnd_cap": 8,
                          "rto_min": "100 ms"}),
             1_500_000_000,
             dict(loss=0.05, latency=10_000_000, sends_budget=16)),
}


@pytest.mark.parametrize("qb", [0, 8], ids=["flat", "bucketed"])
@pytest.mark.parametrize("k", [1, 4], ids=["k1", "k4"])
@pytest.mark.parametrize("case", sorted(_CASES), ids=sorted(_CASES))
def test_tracing_is_bit_identical_and_complete(case, k, qb):
    """The ISSUE acceptance gate: tracing on vs off across the full
    model x layout x K matrix, plus ring completeness/monotonicity."""
    model, hosts, stop, kw = _CASES[case]
    s_off, _ = _run(model, hosts, stop, k=k, qb=qb, trace=False, **kw)
    s_on, tracer = _run(model, hosts, stop, k=k, qb=qb, trace=True, **kw)
    off, on = jax.device_get(s_off.stats), jax.device_get(s_on.stats)

    np.testing.assert_array_equal(np.asarray(off.digest), np.asarray(on.digest))
    np.testing.assert_array_equal(np.asarray(off.events), np.asarray(on.events))
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(s_off.queue.dropped)),
        np.asarray(jax.device_get(s_on.queue.dropped)),
    )
    for field in ("pkts_sent", "pkts_lost", "pkts_codel_dropped",
                  "pkts_budget_dropped", "pkts_delivered", "q_occ_hwm"):
        np.testing.assert_array_equal(
            np.asarray(getattr(off, field)), np.asarray(getattr(on, field)),
            err_msg=field,
        )

    rounds = int(on.rounds)
    assert tracer.rounds == rounds and tracer.lost == 0
    rows = tracer.rows()
    assert rows.shape == (1, rounds, len(TRACE_FIELDS))
    r = rows[0]
    # monotone round indices starting at 0, strictly increasing windows
    np.testing.assert_array_equal(r[:, COL_ROUND], np.arange(rounds))
    assert (np.diff(r[:, COL_WINDOW_START]) > 0).all()
    assert (r[:, COL_WINDOW_END] > r[:, COL_WINDOW_START]).all()
    # per-round counters reconcile with the cumulative device counters
    assert r[:, COL_EVENTS].sum() == int(np.asarray(on.events).sum())
    assert r[:, COL_MICROSTEPS].sum() == int(np.asarray(on.microsteps).sum())
    # ring's per-round occupancy max == stats' per-host high-water max
    assert r[:, COL_OCC_HWM].max() == int(np.asarray(on.q_occ_hwm).max())


def test_trace_ring_wrap_counts_lost_rows():
    """A ring smaller than the rounds between drains drops the OLDEST
    rows and counts them in `lost` — the newest rows stay intact."""
    model, hosts, stop, kw = _CASES["phold"]
    state, tracer = _run(model, hosts, stop, trace=True, ring=4, **kw)
    rounds = int(jax.device_get(state.stats.rounds))
    # the harness drains once per chunk; this workload finishes inside one
    # 64-round chunk, so a 4-slot ring must have wrapped
    assert rounds > 4
    assert tracer.lost == rounds - 4
    assert tracer.rounds == 4
    r = tracer.rows()[0]
    np.testing.assert_array_equal(
        r[:, COL_ROUND], np.arange(rounds - 4, rounds)
    )
    assert (r[:, COL_NEXT_TIME] > 0).all()


def test_fresh_tracer_adopts_ring_cursor():
    """The checkpoint-resume shape: a FRESH tracer handed a state whose
    ring already holds rows (device cursor > 0) must sync to the current
    cursor — not replay pre-existing rows as new rounds or count them as
    ring losses."""
    model, hosts, stop, kw = _CASES["phold"]
    state, _ = _run(model, hosts, stop, trace=True, **kw)
    assert int(jax.device_get(state.trace.cursor).max()) > 0
    b = RoundTracer(RING)
    b.sync_cursor(state.trace)
    assert b.drain(state.trace) == 0
    assert b.rounds == 0 and b.lost == 0
    assert b.rows().shape[1] == 0


# the two Simulation legs of the smoke test run in a SUBPROCESS via
# tests/subproc.py (the shared isolation helper for this box's documented
# jaxlib-0.4.37 heap corruption in compiled Simulation runs). The
# engine-harness matrix above is the primary gate and is stable in-process;
# this leg gates the DRIVER wiring.
_SMOKE_SCRIPT = """
import json, sys
from shadow_tpu.config.options import ConfigOptions
from shadow_tpu.sim import Simulation

def cfg(tmp, trace):
    return ConfigOptions.from_dict({
        "general": {"stop_time": "1 s", "seed": 7, "data_directory": tmp,
                    "heartbeat_interval": None},
        "network": {"graph": {"type": "1_gbit_switch"}},
        "experimental": {"event_queue_capacity": 16, "rounds_per_chunk": 8},
        "observability": {"trace": trace},
        "hosts": {
            "server": {"network_node_id": 0,
                       "processes": [{"model": "udp_echo",
                                      "model_args": {"role": "server"}}]},
            "cli": {"count": 3, "network_node_id": 0,
                    "processes": [{
                        "model": "udp_echo",
                        "model_args": {"role": "client", "peer": "server",
                                       "interval": "100 ms",
                                       "size_bytes": 256}}]},
        },
    })

off_dir, on_dir = sys.argv[1], sys.argv[2]
sim_off = Simulation(cfg(off_dir, False), world=1)
rep_off = sim_off.run()
sim_on = Simulation(cfg(on_dir, True), world=1)
rep_on = sim_on.run()
sim_on.write_outputs(report=rep_on)
print(json.dumps({"off": rep_off, "on": rep_on}))
"""


def test_simulation_trace_smoke(tmp_path):
    """Tier-1 smoke (the ISSUE's CI satellite): a tiny echo sim with
    tracing on exports a valid Chrome trace with one round record per
    completed round, digests match the untraced run, and
    tools/trace_summary.py consumes the file."""
    from tests.subproc import run_isolated_json

    reps = run_isolated_json(
        _SMOKE_SCRIPT, str(tmp_path / "off"), str(tmp_path / "on")
    )
    rep_off, rep_on = reps["off"], reps["on"]

    assert rep_on["determinism_digest"] == rep_off["determinism_digest"]
    assert rep_on["events_processed"] == rep_off["events_processed"]
    assert rep_on["rounds"] == rep_off["rounds"]
    assert rep_on["trace"]["rounds_traced"] == rep_on["rounds"]
    assert rep_on["trace"]["rounds_lost"] == 0
    assert rep_on["queue_occupancy_hwm"] >= 1
    assert len(rep_on["per_host"]["events_processed"]) == 4

    trace_path = tmp_path / "on" / "trace.json"
    with open(trace_path) as f:
        trace = json.load(f)  # valid JSON or this raises
    rounds = [e for e in trace["traceEvents"] if e.get("cat") == "round"]
    assert len(rounds) == rep_on["rounds"]
    idx = [e["args"]["round"] for e in rounds]
    assert idx == sorted(idx) == list(range(rep_on["rounds"]))
    starts = [e["args"]["window_start"] for e in rounds]
    assert all(b > a for a, b in zip(starts, starts[1:]))

    metrics = (tmp_path / "on" / "metrics.prom").read_text()
    assert f"shadow_tpu_rounds_total {rep_on['rounds']}" in metrics
    assert "shadow_tpu_queue_occupancy_hwm" in metrics
    # exposition validity: one HELP/TYPE block per metric name even though
    # the report's extra gauges collide with built-ins (q_occ_hwm etc.)
    names = [ln.split()[2] for ln in metrics.splitlines()
             if ln.startswith("# TYPE")]
    assert len(names) == len(set(names))

    # per-host occupancy high-water rides in host-stats.json (tracked
    # unconditionally; sanity-check on the traced run's output dir)
    hs = json.load(open(tmp_path / "on" / "hosts" / "server" /
                        "host-stats.json"))
    assert hs["queue_occupancy_hwm"] >= 1

    out = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(__file__), "..", "tools",
                      "trace_summary.py"),
         str(trace_path), "--json"],
        capture_output=True, text=True, timeout=60,
    )
    assert out.returncode == 0, out.stderr
    summary = json.loads(out.stdout)
    assert summary["rounds"] == rep_on["rounds"]
    assert summary["phases"]["all"]["events"]["sum"] \
        == rep_on["events_processed"]


def test_metrics_text_deduplicates_colliding_extras():
    """Report fields passed as extra gauges can collide with built-in
    metric names (queue_occupancy_hwm does); the exporter must keep one
    HELP/TYPE block per name or the exposition file is unscrapeable."""
    t = RoundTracer(4)
    text = t.to_metrics_text(
        extra={"queue_occupancy_hwm": 5, "rounds": 1, "skipped": "str"}
    )
    names = [ln.split()[2] for ln in text.splitlines()
             if ln.startswith("# TYPE")]
    assert len(names) == len(set(names))
    assert "shadow_tpu_rounds" in names  # non-colliding extras still land
    assert not any("skipped" in n for n in names)  # non-numerics filtered


def test_observability_options_parse():
    from shadow_tpu.config.options import ConfigError, ObservabilityOptions

    o = ObservabilityOptions.from_dict(None)
    assert not o.trace and o.trace_file == "trace.json"
    assert o.metrics_file == "metrics.prom" and o.profile_dir is None
    o = ObservabilityOptions.from_dict(
        {"trace": True, "trace_file": "t.json", "metrics_file": None,
         "profile_dir": "/tmp/prof"}
    )
    assert o.trace and o.metrics_file is None and o.profile_dir == "/tmp/prof"
    # null disables an export (it must NOT coerce to a file named "None")
    o = ObservabilityOptions.from_dict({"trace": True, "trace_file": None})
    assert o.trace_file is None
    with pytest.raises(ConfigError, match="unknown observability"):
        ObservabilityOptions.from_dict({"nope": 1})
    with pytest.raises(ConfigError, match="trace_file"):
        ObservabilityOptions.from_dict({"trace_file": ""})


def test_heartbeat_regex_old_and_new():
    """tools/parse_shadow.py must parse both the extended heartbeat line
    (ici_bytes / q_hwm) and pre-PR-3 lines without them."""
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from tools.parse_shadow import HEARTBEAT_RE

    new = ("[heartbeat] sim_time=1.000s wall=2.50s events=100 rounds=10 "
           "msteps/round=3.0 ev/mstep=3.33 ici_bytes=4096 q_hwm=7 "
           "ratio=0.40x rss_gib=1.00")
    m = HEARTBEAT_RE.search(new)
    assert m and m.group("ici_bytes") == "4096" and m.group("q_hwm") == "7"
    assert m.group("ratio") == "0.40"
    old = ("[heartbeat] sim_time=1.000s wall=2.50s events=100 rounds=10 "
           "msteps/round=3.0 ev/mstep=3.33 ratio=0.40x rss_gib=1.00")
    m = HEARTBEAT_RE.search(old)
    assert m and m.group("ici_bytes") is None
    assert m.group("gear") is None
    assert m.group("ratio") == "0.40"
    # PR 4 adaptive-exchange field: gear= rides between q_hwm and ratio on
    # merge_gears runs; lines without it (above) must keep parsing
    geared = ("[heartbeat] sim_time=1.000s wall=2.50s events=100 rounds=10 "
              "msteps/round=3.0 ev/mstep=3.33 ici_bytes=4096 q_hwm=7 "
              "gear=2 ratio=0.40x rss_gib=1.00")
    m = HEARTBEAT_RE.search(geared)
    assert m and m.group("gear") == "2" and m.group("q_hwm") == "7"
    # the hybrid driver's windows= form carries gear= too
    hybrid = ("[heartbeat] sim_time=1.000s wall=2.50s windows=10 "
              "gear=4 ratio=0.40x")
    m = HEARTBEAT_RE.search(hybrid)
    assert m and m.group("gear") == "4" and m.group("windows") == "10"
