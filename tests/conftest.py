"""Test env: force an 8-device virtual CPU mesh BEFORE jax backends initialize.

Multi-chip sharding is validated the way the driver does it — N virtual CPU
devices via --xla_force_host_platform_device_count (real multi-chip hardware is
not available in this environment). This mirrors the reference's test posture:
"multi-node" is many simulated hosts in one process (SURVEY.md §4.7).

Note: this box's sitecustomize registers the `axon` TPU plugin and forces
`jax_platforms="axon,cpu"`, overriding the JAX_PLATFORMS env var — so we must
override back via jax.config before any backend is touched.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    # tier-1 runs `-m 'not slow'`; register the marker so opting a heavy
    # leg out (e.g. the campaign end-to-end, covered by the
    # TIER1_CAMPAIGN stage instead) never warns
    config.addinivalue_line("markers", "slow: excluded from tier-1")
