"""Occupancy-adaptive merge gears (`experimental.merge_gears`, PR 4):
the shed-exact replay equivalence gate plus controller/ladder units.

The contract mirrors the earlier bit-identity PRs: running the exchange
merge at ANY gear ladder — including chunks that shed and replay one gear
up from the pre-chunk snapshot — produces digests, per-host event counts,
and drop counters bit-identical to the full-width engine, across
echo/phold/tgen, flat and bucketed queue layouts, K in {1, 4}, and
world in {1, 8} (gather AND alltoall exchanges). The gear-1 start forces
real sheds, so the replay path is exercised, not just reachable.
"""

from __future__ import annotations

import jax
import numpy as np
import pytest

from shadow_tpu.core import Engine
from shadow_tpu.core.checkpoint import restore_snapshot, snapshot_state
from shadow_tpu.core.gears import (
    GearController,
    resolve_gear_ladder,
    run_adaptive_chunk,
)
from tests.engine_harness import build_sim, mk_hosts

# the test_tracer workload trio: short horizons, exchange-heavy enough to
# exercise the merge every round
_CASES = {
    "phold": ("phold", mk_hosts(8, {"mean_delay": "20 ms", "population": 3}),
              300_000_000, dict(loss=0.1)),
    "echo": ("udp_echo",
             [dict(host_id=0, name="server", start_time=0,
                   model_args={"role": "server"})]
             + [dict(host_id=i, name=f"c{i}", start_time=0,
                     model_args={"role": "client", "peer": "server",
                                 "interval": "4 ms", "size_bytes": 2000})
                for i in range(1, 5)],
             200_000_000, dict(bw_bits=2_000_000, loss=0.05)),
    "tgen": ("tgen_tcp",
             mk_hosts(5, {"flow_segs": 8, "flows": 1, "cwnd_cap": 8,
                          "rto_min": "100 ms"}),
             1_500_000_000,
             dict(loss=0.05, latency=10_000_000, sends_budget=16)),
}


def _build(model, hosts, stop, world=1, **kw):
    cfg, m, params, mstate, events = build_sim(
        model, hosts, stop, world=world, **kw
    )
    mesh = None
    if world > 1:
        mesh = jax.sharding.Mesh(
            np.array(jax.devices()[:world]), ("hosts",)
        )
    eng = Engine(cfg, m, mesh)
    state, params = eng.init_state(params, mstate, events, seed=1)
    return cfg, eng, state, params


def _run_full(model, hosts, stop, world=1, **kw):
    _, eng, state, params = _build(model, hosts, stop, world, **kw)
    while not bool(state.done):
        state = eng.run_chunk(state, params)
    return state


def _run_geared(model, hosts, stop, world=1, start_low=True, **kw):
    """Drive the gear ladder exactly like the drivers do (the shared
    run_adaptive_chunk loop), starting at the LOWEST gear to force sheds."""
    cfg, eng, state, params = _build(model, hosts, stop, world, **kw)
    ladder = resolve_gear_ladder("auto", cfg.sends_per_host_round)
    ctl = GearController(ladder)
    if start_low:
        ctl.gear = ladder[0]
    while not bool(state.done):
        state, _, _ = run_adaptive_chunk(
            ctl, state, lambda st, g: eng.run_chunk_gear(st, params, g)
        )
    return state, ctl


def _assert_identical(full, geared):
    f = jax.device_get(full.stats)
    g = jax.device_get(geared.stats)
    np.testing.assert_array_equal(np.asarray(f.digest), np.asarray(g.digest))
    np.testing.assert_array_equal(np.asarray(f.events), np.asarray(g.events))
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(full.queue.dropped)),
        np.asarray(jax.device_get(geared.queue.dropped)),
    )
    for field in ("pkts_sent", "pkts_lost", "pkts_codel_dropped",
                  "pkts_budget_dropped", "pkts_delivered", "q_occ_hwm"):
        np.testing.assert_array_equal(
            np.asarray(getattr(f, field)), np.asarray(getattr(g, field)),
            err_msg=field,
        )
    # per-SHARD counters ([world]-shaped) compare by total across mesh shapes
    assert int(np.asarray(g.a2a_shed).sum()) == int(np.asarray(f.a2a_shed).sum())
    # accepted chunks never shed (shedding attempts were discarded)
    assert int(np.asarray(g.gear_shed).max()) == 0


@pytest.mark.parametrize("qb", [0, 8], ids=["flat", "bucketed"])
@pytest.mark.parametrize("k", [1, 4], ids=["k1", "k4"])
@pytest.mark.parametrize("case", sorted(_CASES), ids=sorted(_CASES))
def test_gear_ladder_bit_identical_with_forced_replay(case, k, qb):
    """The acceptance gate: a gear-ladder run started at the BOTTOM gear
    (so low-width chunks genuinely shed and replay) finishes bit-identical
    to the full-width engine — digests, events, every drop counter."""
    model, hosts, stop, kw = _CASES[case]
    full = _run_full(model, hosts, stop, queue_block=qb,
                     microstep_events=k, **kw)
    geared, ctl = _run_geared(model, hosts, stop, queue_block=qb,
                              microstep_events=k, **kw)
    _assert_identical(full, geared)
    # the gear-1 start must have forced at least one shed->replay (these
    # workloads all stage multi-send rounds)
    assert ctl.replays > 0


@pytest.mark.parametrize("exchange", ["gather", "alltoall"])
def test_gear_ladder_mesh_invariant(exchange):
    """world=8 dryrun (both exchange strategies): sheds are psum'd so the
    chunk abort is mesh-uniform, and the replayed result matches the
    single-device full-width digest."""
    model, hosts, stop, kw = _CASES["phold"]
    full = _run_full(model, hosts, stop, world=1, **kw)
    geared, ctl = _run_geared(
        model, hosts, stop, world=8, exchange=exchange, **kw
    )
    _assert_identical(full, geared)
    assert ctl.replays > 0


@pytest.mark.parametrize("qb", [0, 8], ids=["flat", "bucketed"])
def test_merge_rows_and_gears_compose(qb):
    """merge_rows (post-sort POSITIONAL shedding into queue.dropped) and
    gears (pre-sort width truncation with abort-replay) must compose: the
    sorted sequence of valid entries + tokens is identical at any
    non-shedding gear (the slice drops only trailing invalid rows), so a
    merge_rows bound sheds the SAME rows at every gear — digests, events,
    and the merge_rows drop counts all bit-identical to the full-width
    run under the same bound, with the bound genuinely firing."""
    model, hosts, stop, kw = _CASES["phold"]
    # tight enough that overflow rounds shed by sorted position: 8 hosts
    # x up to 8 sends + 9 tokens can exceed 24 sorted positions
    mr = 24
    full = _run_full(model, hosts, stop, queue_block=qb, merge_rows=mr, **kw)
    geared, ctl = _run_geared(model, hosts, stop, queue_block=qb,
                              merge_rows=mr, **kw)
    _assert_identical(full, geared)
    assert ctl.replays > 0  # the gear-1 start still forced replays
    # the merge_rows bound itself fired (otherwise this tests nothing) —
    # identical drops on both sides already asserted above
    assert int(np.asarray(jax.device_get(full.queue.dropped)).sum()) > 0


def test_snapshot_survives_donation_and_repeated_restores():
    """The replay loop's memory contract: the snapshot is an independent
    device copy (the jitted chunk donates its input), and each restore
    hands out a FRESH copy so a mid-ladder replay can shed again and
    restore again."""
    model, hosts, stop, kw = _CASES["phold"]
    _, eng, state, params = _build(model, hosts, stop, **kw)
    snap = snapshot_state(state)
    now0 = int(state.now)
    state = eng.run_chunk(state, params)  # donates its input buffers
    assert int(state.now) > now0
    r1 = restore_snapshot(snap)
    r1 = eng.run_chunk(r1, params)  # consumes the first restore...
    r2 = restore_snapshot(snap)  # ...snapshot still serves a second
    assert int(r2.now) == now0
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(r2.stats.digest)),
        np.asarray(jax.device_get(snap.stats.digest)),
    )
    # and the two replays from the same snapshot are bit-identical
    r2 = eng.run_chunk(r2, params)
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(r1.stats.digest)),
        np.asarray(jax.device_get(r2.stats.digest)),
    )


def test_outbox_hwm_tracks_max_sends():
    """stats.outbox_hwm (always on) records the max sends any one host
    staged in a round — on a full-width run it never resets, so it bounds
    every round's per-host send count and is > 0 on send-heavy work."""
    model, hosts, stop, kw = _CASES["phold"]
    state = _run_full(model, hosts, stop, **kw)
    hwm = int(np.asarray(jax.device_get(state.stats.outbox_hwm)).max())
    budget = 8  # harness default sends_budget
    assert 0 < hwm <= budget


# ------------------------------------------------------------------ units


def test_resolve_gear_ladder():
    assert resolve_gear_ladder(0, 8) == []
    assert resolve_gear_ladder(None, 8) == []
    assert resolve_gear_ladder(False, 8) == []
    assert resolve_gear_ladder("off", 8) == []  # the documented string form
    assert resolve_gear_ladder("auto", 8) == [1, 2, 4, 8]
    assert resolve_gear_ladder(True, 8) == [1, 2, 4, 8]
    assert resolve_gear_ladder("auto", 24) == [3, 6, 12, 24]
    # tiny budgets collapse duplicate rungs; a ladder of only the full
    # width is no ladder at all
    assert resolve_gear_ladder("auto", 1) == []
    assert resolve_gear_ladder("auto", 2) == [1, 2]
    # explicit lists: sorted, deduped, full width appended
    assert resolve_gear_ladder([4, 1], 8) == [1, 4, 8]
    assert resolve_gear_ladder([8, 2], 8) == [2, 8]
    assert resolve_gear_ladder(2, 8) == [2, 8]
    assert resolve_gear_ladder([8], 8) == []
    with pytest.raises(ValueError):
        resolve_gear_ladder([0, 4], 8)
    with pytest.raises(ValueError):
        resolve_gear_ladder([9], 8)
    with pytest.raises(ValueError):
        resolve_gear_ladder("fast", 8)


def test_gear_controller_policy():
    ctl = GearController([1, 2, 4, 8], down_lag=2)
    assert ctl.gear == 8  # starts at the top (boot occupancy unknown)
    # hwm 1 fits gear 2 (strict headroom) — downshift after down_lag chunks
    assert ctl.note_chunk(8, 1) == 8
    assert ctl.note_chunk(8, 1) == 2
    # exactly-filled width steps up preemptively (hwm == gear)
    assert ctl.note_chunk(2, 2) == 4
    # a shed steps one gear up and counts a replay
    ctl2 = GearController([1, 2, 4, 8])
    ctl2.gear = 1
    assert ctl2.note_shed() == 2
    assert ctl2.note_shed() == 4
    assert ctl2.note_shed() == 8
    assert ctl2.note_shed() == 8  # top clamps
    assert ctl2.replays == 4
    # a shed carrying the aborted chunk's high-water jumps straight to a
    # fitting gear (one replay, not a rung-by-rung walk)
    ctl3 = GearController([1, 2, 4, 8])
    ctl3.gear = 1
    assert ctl3.note_shed(7) == 8
    assert ctl3.replays == 1
    ctl3.gear = 1
    assert ctl3.note_shed(2) == 4  # fit(2)=4 beats the one-rung step
    # accepted-chunk histogram + report shape
    ctl2.note_chunk(8, 3)
    rep = ctl2.report()
    assert rep["ladder"] == [1, 2, 4, 8]
    assert rep["chunks_per_gear"] == {"8": 1}
    assert rep["replays"] == 4


def test_adaptive_chunk_skips_controller_on_zero_round_window():
    """Hybrid guarded windows can retire ZERO rounds (probe fires at
    entry); run_adaptive_chunk must not feed the controller those
    windows' hwm of 0 — two idle windows would otherwise downshift past
    real occupancy and buy the next busy window a guaranteed replay."""
    from typing import Any, NamedTuple

    import jax.numpy as jnp

    class _Stats(NamedTuple):
        gear_shed: Any
        outbox_hwm: Any
        rounds: Any

    class _State(NamedTuple):
        stats: _Stats

    def st(rounds):
        return _State(_Stats(
            jnp.zeros((1,), jnp.int64), jnp.zeros((1,), jnp.int64),
            jnp.asarray(rounds, jnp.int64),
        ))

    ctl = GearController([1, 2, 4, 8], down_lag=1)
    # idle window (rounds unchanged): controller untouched
    _, gear, hwm = run_adaptive_chunk(ctl, st(0), lambda s, g: s, rounds0=0)
    assert ctl.chunks == {} and ctl.gear == 8 and hwm == 0
    # a window that advanced rounds feeds it (hwm 0 -> bottom at lag 1)
    _, gear, _ = run_adaptive_chunk(ctl, st(1), lambda s, g: s, rounds0=0)
    assert ctl.chunks == {8: 1} and ctl.gear == 1


def test_engine_config_rejects_bad_gear():
    from shadow_tpu.core import EngineConfig

    with pytest.raises(ValueError, match="gear_cols"):
        EngineConfig(num_hosts=4, stop_time=1, sends_per_host_round=4,
                     gear_cols=5)
    with pytest.raises(ValueError, match="gear_cols"):
        EngineConfig(num_hosts=4, stop_time=1, gear_cols=-1)


def test_merge_gears_config_parse():
    from shadow_tpu.config.options import ConfigError, ExperimentalOptions

    assert ExperimentalOptions.from_dict(None).merge_gears == 0
    assert ExperimentalOptions.from_dict(
        {"merge_gears": "auto"}
    ).merge_gears == "auto"
    assert ExperimentalOptions.from_dict(
        {"merge_gears": "off"}
    ).merge_gears == 0
    assert ExperimentalOptions.from_dict(
        {"merge_gears": [2, 4]}
    ).merge_gears == [2, 4]
    assert ExperimentalOptions.from_dict({"merge_gears": 2}).merge_gears == 2
    assert ExperimentalOptions.from_dict({"merge_gears": 0}).merge_gears == 0
    with pytest.raises(ConfigError, match="merge_gears"):
        ExperimentalOptions.from_dict({"merge_gears": "fast"})
    with pytest.raises(ConfigError, match="merge_gears"):
        ExperimentalOptions.from_dict({"merge_gears": [2, "x"]})


def test_gear_shed_count_exact():
    import jax.numpy as jnp

    from shadow_tpu.ops.merge import gear_shed_count

    sent = jnp.asarray([0, 1, 2, 5, 8], jnp.int32)
    assert int(gear_shed_count(sent, 2)) == 0 + 0 + 0 + 3 + 6
    assert int(gear_shed_count(sent, 8)) == 0  # full width never sheds
