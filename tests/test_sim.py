"""End-to-end config-driven simulation tests (the analogue of the reference's
system tests: a YAML config in, deterministic results + data-dir out;
src/test/config + determinism suites)."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from shadow_tpu.config.options import ConfigError, load_config, merge_cli_overrides
from shadow_tpu.sim import Simulation, expand_hosts

ECHO_YAML = """
general:
  stop_time: 5 s
  seed: 7
network:
  graph:
    type: gml
    inline: |
      graph [
        directed 0
        node [ id 0 host_bandwidth_down "100 Mbit" host_bandwidth_up "100 Mbit" ]
        node [ id 1 ]
        edge [ source 0 target 0 latency "1 ms" ]
        edge [ source 0 target 1 latency "25 ms" packet_loss 0.0 ]
        edge [ source 1 target 1 latency "1 ms" ]
      ]
hosts:
  server:
    network_node_id: 0
    processes:
      - model: udp_echo
        model_args: { role: server }
  client:
    count: 3
    network_node_id: 1
    bandwidth_up: 10 Mbit
    bandwidth_down: 10 Mbit
    processes:
      - model: udp_echo
        model_args: { role: client, peer: server, interval: 1 s, size_bytes: 256 }
"""


def _build(yaml_text=ECHO_YAML, **over):
    cfg = load_config(yaml_text, is_text=True)
    if over:
        cfg = merge_cli_overrides(cfg, {k: str(v) for k, v in over.items()})
    return cfg


def test_expand_hosts_ips_and_bandwidth():
    cfg = _build()
    sim = Simulation(cfg, world=1)
    names = [h.name for h in sim.hosts]
    assert names == sorted(names) and "server" in names and "client2" in names
    assert len({h.ip for h in sim.hosts}) == 4
    by_name = {h.name: h for h in sim.hosts}
    assert by_name["server"].bw_down_bits == 100_000_000  # from graph node
    assert by_name["client1"].bw_up_bits == 10_000_000  # per-host override


def test_echo_end_to_end():
    cfg = _build()
    sim = Simulation(cfg, world=1)
    report = sim.run()
    m = report["model_report"]
    # 3 clients x 5 ticks (t=0..4s); each RTT = 2*25ms
    assert m["requests_sent"] == 15
    assert m["requests_served"] == 15
    # last responses (sent t=4s) arrive 4.05s < 5s: all come back
    assert m["responses_received"] == 15
    assert m["mean_rtt_ms"] == pytest.approx(50.0, abs=1.0)
    assert report["packets_lost"] == 0
    assert report["events_processed"] > 0


def test_determinism_across_runs_and_world(tmp_path):
    cfg = _build()
    d1 = Simulation(cfg, world=1)
    d1.run()
    d2 = Simulation(cfg, world=1)
    d2.run()
    np.testing.assert_array_equal(d1.host_digests(), d2.host_digests())
    # world=2 pads 4 hosts onto 2 shards; digests must not change
    d3 = Simulation(cfg, world=2)
    d3.run()
    np.testing.assert_array_equal(d1.host_digests(), d3.host_digests())


def test_write_outputs(tmp_path):
    cfg = _build()
    cfg.general.data_directory = str(tmp_path / "data")
    sim = Simulation(cfg, world=1)
    sim.run()
    out = sim.write_outputs()
    with open(os.path.join(out, "sim-stats.json")) as f:
        stats = json.load(f)
    assert stats["packets_delivered"] == 30  # 15 requests + 15 responses
    assert os.path.exists(os.path.join(out, "processed-config.yaml"))
    with open(os.path.join(out, "hosts", "server", "host-stats.json")) as f:
        server = json.load(f)
    assert server["packets_delivered"] == 15
    assert server["ip"]


def test_world_padding_uneven():
    # 4 hosts over world=8 devices -> padded to 8, inert pads don't perturb
    cfg = _build()
    sim = Simulation(cfg, world=8)
    assert sim.engine_cfg.num_hosts == 8
    report = sim.run()
    assert report["model_report"]["responses_received"] == 15


def test_config_errors():
    with pytest.raises(ConfigError, match="one device-model process"):
        Simulation(
            _build(
                """
general: { stop_time: 1 s }
hosts:
  a:
    processes: []
""".replace("processes: []", "processes: [{model: udp_echo, model_args: {role: server}}, {model: timer}]")
            ),
            world=1,
        )
    with pytest.raises(ConfigError, match="no hosts"):
        Simulation(_build("general: { stop_time: 1 s }\nhosts: {}"), world=1)


def test_cli_round_trip(tmp_path):
    cfg_path = tmp_path / "sim.yaml"
    cfg_path.write_text(ECHO_YAML)
    data_dir = tmp_path / "data"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [
            sys.executable,
            "-m",
            "shadow_tpu",
            str(cfg_path),
            "--print-stats",
            "--general.data_directory",
            str(data_dir),
            "--general.stop_time=2 s",
            "--general.parallelism=1",
        ],
        capture_output=True,
        text=True,
        env=env,
        cwd="/root/repo",
    )
    assert r.returncode == 0, r.stderr
    stats = json.loads(r.stdout)
    assert stats["model_report"]["requests_sent"] == 6  # 3 clients x 2 ticks
    assert (data_dir / "sim-stats.json").exists()
    assert "done: simulated" in r.stderr


def test_cli_dry_run_and_bad_config(tmp_path):
    cfg_path = tmp_path / "sim.yaml"
    cfg_path.write_text(ECHO_YAML)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, "-m", "shadow_tpu", str(cfg_path), "--dry-run"],
        capture_output=True, text=True, env=env, cwd="/root/repo",
    )
    assert r.returncode == 0 and "config ok: 4 hosts" in r.stderr
    r2 = subprocess.run(
        [sys.executable, "-m", "shadow_tpu", str(cfg_path), "--bogus.key=1", "--dry-run"],
        capture_output=True, text=True, env=env, cwd="/root/repo",
    )
    assert r2.returncode == 2 and "config error" in r2.stderr


def test_modeled_sim_pcap_capture(tmp_path):
    """pcap_enabled on a device-modeled host produces a parseable eth0.pcap
    with synthesized UDP frames, byte-identical across two runs (closes the
    round-1 'silently ignored for modeled sims' gap)."""
    import struct as _struct

    from shadow_tpu.config.options import ConfigOptions
    from shadow_tpu.sim import Simulation

    def once(d):
        cfg = ConfigOptions.from_dict({
            "general": {"stop_time": "300 ms", "seed": 3,
                        "data_directory": str(d)},
            "network": {"graph": {"type": "1_gbit_switch"}},
            "hosts": {
                "n": {
                    "count": 4,
                    "network_node_id": 0,
                    "host_options": {"pcap_enabled": True},
                    "processes": [{
                        "model": "phold",
                        "model_args": {"population": 2, "mean_delay": "30 ms"},
                    }],
                }
            },
        })
        sim = Simulation(cfg, world=1)
        sim.run(progress=False)
        caps = {}
        for name in ("n1", "n2", "n3", "n4"):
            p = d / "hosts" / name / "eth0.pcap"
            caps[name] = p.read_bytes()
        return caps

    a = once(tmp_path / "a")
    # parseable header + at least one frame somewhere
    some = False
    for name, blob in a.items():
        magic, = _struct.unpack("<I", blob[:4])
        assert magic == 0xA1B2C3D4
        some = some or len(blob) > 24
    assert some, "no frames captured"
    b = once(tmp_path / "b")
    assert a == b
